"""Self-healing training (ISSUE 10): unified fault injection, in-program
anomaly detection, checkpoint rollback, and the supervised recovery loop.

Covers: the fault registry (trigger modes, flag spec, legacy ckpt-flag
alias, counters), the AnomalyDetector (non-finite + median/MAD spike
classification, policies), CompiledTrainStep health checking (bit-identical
healthy trajectories, in-program update skip, poison detection),
run_resilient end-to-end recovery for every fault class (rollback /
feeder crash / killed save / simulated hang — final losses bit-exact vs the
fault-free run), persistent-fault halt with quarantine + budget, the
Model.fit(auto_checkpoint=, resilience=) chaos matrix over EVERY registered
fault point, and the satellites: GradScaler consecutive-skip halt, watchdog
thread-stack dumps, feeder crash context, store barrier retry + rank
heartbeats, the except-pass lint, and registry coverage."""
import json
import math
import os
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed.store  # noqa: F401  (registers store.barrier)
import paddle_tpu.nn as nn
from paddle_tpu.core.flags import set_flags
from paddle_tpu.distributed.checkpoint import elastic
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.distributed.resilience import (AnomalyDetector, IncidentLog,
                                               ResilienceHalt,
                                               ResiliencePolicy, faults,
                                               run_resilient)
from paddle_tpu.io.device_feed import DeviceFeeder, FeederWorkerError
from paddle_tpu.parallel import CompiledTrainStep

# every registered injection point, as LITERALS (the coverage test greps for
# them; test_chaos_matrix_covers_registry pins this list to the registry so
# a new point cannot land without a chaos test)
CHAOS_POINTS = [
    "ckpt.after_commit", "ckpt.after_metadata", "ckpt.after_shard_write",
    "ckpt.after_snapshot", "ckpt.before_commit", "ckpt.before_rename",
    "feeder.collate", "feeder.device_put", "step.grads", "store.barrier",
    "watchdog.hang",
]
# the serving half of the registry (PR 11/12): registered at import of
# paddle_tpu.serving.replica/router/engine, exercised by the routed chaos
# matrix in test_router.py (transport points), the speculative-decode
# degradation test in test_serving.py (serving.spec.verify_mismatch), and
# the host-tier degradation tests in test_kv_hierarchy.py
# (serving.kv.promote_fail), and the disaggregated prefill/decode
# exactly-once tests in test_disagg.py (serving.prefill.kill,
# serving.handoff.drop) — these points fire on serving traffic, so
# injecting them into a Model.fit run would test nothing
SERVING_CHAOS_POINTS = [
    "serving.dispatch.drop", "serving.handoff.drop",
    "serving.kv.promote_fail", "serving.lora.swap_fail",
    "serving.prefill.kill", "serving.replica.kill",
    "serving.replica.slow", "serving.spec.verify_mismatch",
    "serving.stream.cut",
]


@pytest.fixture(autouse=True)
def _teardown():
    yield
    set_mesh(None)


# -- shared tiny problem ------------------------------------------------------
IN_DIM, N_CLS = 8, 3


def _mlp_data(i, batch=8):
    rng = np.random.RandomState(5000 + i)
    x = rng.randn(batch, IN_DIM).astype(np.float32)
    y = rng.randint(0, N_CLS, (batch,)).astype(np.int64)
    return x, y


def _make_step_factory(n_total):
    """(make_step, make_data) for run_resilient over a small float-input
    MLP — float batches, so the step.grads point poisons a LEAF (NaN grads,
    same-step detection)."""

    def make_data(start):
        def gen():
            for i in range(start, n_total):
                yield _mlp_data(i)
        return gen()

    def make_step(det, arrays=None, meta=None):
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(IN_DIM, 16), nn.ReLU(),
                            nn.Linear(16, N_CLS))
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters())
        if arrays is not None:
            elastic.restore(arrays, meta, net, opt)
        crit = nn.CrossEntropyLoss()
        step = CompiledTrainStep(net, lambda o, l: crit(o, l), opt,
                                 anomaly_detector=det, metrics_every=0)
        if arrays is not None:
            step.load_resume_extras(arrays, meta)
        return step

    return make_step, make_data


class TestFaultRegistry:
    def test_points_register_at_import(self):
        import paddle_tpu.serving.disagg  # noqa: F401 — serving.* points
        import paddle_tpu.serving.replica  # noqa: F401
        import paddle_tpu.serving.router  # noqa: F401
        assert (set(CHAOS_POINTS) | set(SERVING_CHAOS_POINTS)
                <= set(faults.registered()))
        docs = faults.describe()
        for p in CHAOS_POINTS + SERVING_CHAOS_POINTS:
            assert docs[p], f"{p} has no catalog doc"

    def test_unknown_point_raises(self):
        with pytest.raises(KeyError, match="no.such.point"):
            faults.point("no.such.point")
        with pytest.raises(KeyError, match="registered"):
            faults.arm("no.such.point")

    def test_one_shot(self):
        faults.reset()
        faults.arm("feeder.collate")
        with pytest.raises(faults.FaultInjected) as ei:
            faults.point("feeder.collate")
        assert ei.value.point == "feeder.collate"
        faults.point("feeder.collate")  # spent: quiet
        assert faults.hits("feeder.collate") == 2
        assert faults.fired("feeder.collate") == 1

    def test_nth_hit(self):
        faults.reset()
        faults.arm("feeder.device_put", mode="nth", nth=3)
        faults.point("feeder.device_put")
        faults.point("feeder.device_put")
        with pytest.raises(faults.FaultInjected):
            faults.point("feeder.device_put")
        faults.point("feeder.device_put")  # spent

    def test_probabilistic_deterministic_seed(self):
        faults.reset()
        faults.arm("step.grads", mode="prob", p=0.5, seed=123)
        a = [faults.fire_check("step.grads") for _ in range(32)]
        faults.reset()
        faults.arm("step.grads", mode="prob", p=0.5, seed=123)
        b = [faults.fire_check("step.grads") for _ in range(32)]
        assert a == b and any(a) and not all(a)

    def test_always_until_disarm(self):
        faults.reset()
        faults.arm("store.barrier", mode="always")
        for _ in range(3):
            with pytest.raises(faults.FaultInjected):
                faults.point("store.barrier")
        faults.disarm("store.barrier")
        faults.point("store.barrier")

    def test_flag_spec_arming(self):
        faults.reset()
        set_flags({"fault_injection": "feeder.collate:nth=2"})
        faults.point("feeder.collate")
        with pytest.raises(faults.FaultInjected):
            faults.point("feeder.collate")
        faults.point("feeder.collate")  # spent while flag unchanged
        # editing the flag re-arms from scratch
        set_flags({"fault_injection": "feeder.collate"})
        with pytest.raises(faults.FaultInjected):
            faults.point("feeder.collate")
        set_flags({"fault_injection": ""})

    def test_bad_flag_spec_raises(self):
        faults.reset()
        set_flags({"fault_injection": "feeder.collate:bogus=1"})
        with pytest.raises(ValueError, match="bogus"):
            faults.point("feeder.collate")
        # a typo'd mode must fail loudly, not silently never fire
        set_flags({"fault_injection": "feeder.collate:mode=alwys"})
        with pytest.raises(ValueError, match="alwys"):
            faults.point("feeder.collate")
        set_flags({"fault_injection": "feeder.collate:mode=prob"})
        with pytest.raises(ValueError, match="p>0"):
            faults.point("feeder.collate")
        set_flags({"fault_injection": ""})

    def test_malformed_flag_spec_fails_at_config_time(self):
        """check_flag_spec parses the flag NOW: a typo'd spec must fail at
        supervisor/fit startup, not surface at the first injection site hit
        (which may be the feeder worker thread, where the ValueError would
        be wrapped as FeederWorkerError and misdiagnosed — and retried —
        as an input-pipeline fault)."""
        faults.reset()
        try:
            set_flags({"fault_injection": "feeder.collate:nht=3"})
            with pytest.raises(ValueError, match="nht"):
                faults.check_flag_spec()
            # a typo'd POINT NAME is as silent-deadly as a typo'd option:
            # the chaos run would pass cleanly while testing nothing
            set_flags({"fault_injection": "fedeer.collate:nth=1"})
            with pytest.raises(KeyError, match="fedeer"):
                faults.check_flag_spec()
        finally:
            set_flags({"fault_injection": ""})
        faults.check_flag_spec()  # a clean spec parses quietly

    def test_legacy_ckpt_flag_still_arms(self, tmp_path):
        """The PR-8 kill-point contract survives the migration: the old
        string flag arms ckpt.<point> in always mode and raises
        CheckpointFaultInjected through a REAL save."""
        faults.reset()
        set_flags({"ckpt_fault_injection": "before_rename"})
        with pytest.raises(elastic.CheckpointFaultInjected,
                           match="before_rename"):
            elastic._maybe_inject("before_rename")
        with pytest.raises(elastic.CheckpointFaultInjected):
            elastic._maybe_inject("before_rename")  # always, not one-shot
        set_flags({"ckpt_fault_injection": ""})
        elastic._maybe_inject("before_rename")
        # and CheckpointFaultInjected IS a registry FaultInjected
        assert issubclass(elastic.CheckpointFaultInjected,
                          faults.FaultInjected)

    def test_new_flag_drives_ckpt_points_through_real_save(self, tmp_path):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        snap = elastic.capture_model(net)
        set_flags({"fault_injection": "ckpt.before_rename"})
        with elastic.CheckpointManager(str(tmp_path)) as mgr:
            with pytest.raises(elastic.CheckpointFaultInjected,
                               match="ckpt.before_rename"):
                mgr.save(snap)
            set_flags({"fault_injection": ""})
            assert mgr.latest() is None  # nothing published
            mgr.save(elastic.capture_model(net))
            assert mgr.latest() is not None


class TestAnomalyDetector:
    def test_nonfinite_and_health_flag(self):
        det = AnomalyDetector(policy="rollback", min_history=4)
        assert det.observe(1, 1.0, 0.0) is None
        a = det.observe(2, 0.9, 1.0)  # finite loss but health says bad
        assert a.kind == "nonfinite" and det.pending is a
        det.clear_pending()
        a2 = det.observe(3, float("nan"), 0.0)
        assert a2.kind == "nonfinite"

    def test_spike_median_mad(self):
        det = AnomalyDetector(policy="rollback", min_history=6, mad_k=8.0)
        for i, l in enumerate([2.0, 1.9, 1.95, 1.85, 1.9, 1.8]):
            assert det.observe(i, l, 0.0) is None
        a = det.observe(7, 40.0, 0.0)
        assert a is not None and a.kind == "loss_spike"
        assert a.detail["threshold"] < 40.0

    def test_downward_drift_is_not_a_spike(self):
        det = AnomalyDetector(policy="rollback", min_history=6, mad_k=8.0)
        loss = 5.0
        for i in range(40):  # a healthy decreasing curve with noise
            loss = loss * 0.97 + 0.01 * math.sin(i)
            assert det.observe(i, loss, 0.0) is None, (i, loss)
        assert det.incidents == []

    def test_gate_adapts_to_permanent_level_shift(self):
        """Flagged losses still enter the rolling window: a genuine level
        shift (lr change, curriculum switch) migrates the median so the
        gate adapts — instead of flagging every subsequent step forever
        against a frozen pre-shift window."""
        det = AnomalyDetector(policy="rollback", window=16, min_history=8,
                              mad_k=8.0)
        for i in range(16):
            assert det.observe(i, 1.0 + 0.01 * (i % 3), 0.0) is None
        flagged = 0
        for i in range(16, 48):  # the curve settles at a higher level
            if det.observe(i, 5.0 + 0.01 * (i % 3), 0.0) is not None:
                det.clear_pending()
                flagged += 1
        assert flagged > 0       # the shift itself is flagged...
        assert flagged < 20      # ...but not every shifted step forever
        assert det.observe(48, 5.0, 0.0) is None  # the gate has adapted

    def test_min_history_gates_spikes(self):
        det = AnomalyDetector(policy="rollback", min_history=8)
        for i in range(5):
            det.observe(i, 1.0, 0.0)
        assert det.observe(6, 1000.0, 0.0) is None  # window too short

    def test_warn_policy_records_without_pending(self):
        det = AnomalyDetector(policy="warn", min_history=4)
        with pytest.warns(UserWarning, match="anomaly detected"):
            det.observe(1, float("inf"), 1.0)
        assert det.pending is None and len(det.incidents) == 1

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            AnomalyDetector(policy="explode")

    def test_nonfinite_tolerance_for_scaler_managed_overflow(self):
        """An isolated overflow under a dynamic GradScaler is EXPECTED
        (scale growth probes the range); only a streak escalates."""
        det = AnomalyDetector(policy="rollback", min_history=4,
                              nonfinite_tolerance=2)
        a1 = det.observe(1, float("nan"), 1.0)
        assert a1.action == "tolerated" and det.pending is None
        det.observe(2, 1.0, 0.0)  # healthy step resets the streak
        a2 = det.observe(3, float("nan"), 1.0)
        assert a2.action == "tolerated" and det.pending is None
        det.observe(4, float("nan"), 1.0)
        a3 = det.observe(5, float("nan"), 1.0)  # 3rd consecutive: escalate
        assert a3.action == "rollback" and det.pending is a3

    def test_step_with_scaler_raises_detector_tolerance(self):
        from paddle_tpu.amp import GradScaler

        paddle.seed(7)
        net = nn.Linear(IN_DIM, N_CLS)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        det = AnomalyDetector(policy="rollback")
        assert det.nonfinite_tolerance == 0
        crit = nn.CrossEntropyLoss()
        CompiledTrainStep(net, lambda o, l: crit(o, l), opt,
                          anomaly_detector=det,
                          grad_scaler=GradScaler(init_loss_scaling=8.0))
        assert det.nonfinite_tolerance == 2

    def test_explicit_tolerance_and_static_scaler_not_overridden(self):
        """The auto-raise is for DYNAMIC scalers' expected growth-interval
        overflows only: an explicit nonfinite_tolerance=0 must be honored,
        and a static (non-dynamic) scaler — where a NaN is a genuine fault
        the scaler will never recover from — must not relax detection."""
        from paddle_tpu.amp import GradScaler

        paddle.seed(7)
        crit = nn.CrossEntropyLoss()

        def step(det, scaler):
            net = nn.Linear(IN_DIM, N_CLS)
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters())
            return CompiledTrainStep(net, lambda o, l: crit(o, l), opt,
                                     anomaly_detector=det, grad_scaler=scaler)

        det = AnomalyDetector(policy="rollback", nonfinite_tolerance=0)
        step(det, GradScaler(init_loss_scaling=8.0))
        assert det.nonfinite_tolerance == 0  # explicit 0 honored
        det2 = AnomalyDetector(policy="rollback")
        step(det2, GradScaler(init_loss_scaling=8.0,
                              use_dynamic_loss_scaling=False))
        assert det2.nonfinite_tolerance == 0  # static scaler: no relaxation

    def test_reset_history_keeps_incidents(self):
        det = AnomalyDetector(policy="rollback", min_history=2)
        det.observe(1, 1.0, 0.0)
        det.observe(2, float("nan"), 1.0)
        det.reset_history()
        assert len(det.history) == 0 and len(det.incidents) == 1


class TestCompiledStepDetection:
    def _step(self, det, seed=7):
        paddle.seed(seed)
        net = nn.Sequential(nn.Linear(IN_DIM, 16), nn.ReLU(),
                            nn.Linear(16, N_CLS))
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters())
        crit = nn.CrossEntropyLoss()
        return CompiledTrainStep(net, lambda o, l: crit(o, l), opt,
                                 anomaly_detector=det)

    def test_healthy_trajectory_bit_identical_with_detection(self):
        det = AnomalyDetector(policy="rollback", min_history=4)
        s_on = self._step(det)
        s_off = self._step(False)
        x, y = _mlp_data(0)
        on = [float(s_on(x, y)) for _ in range(4)]
        off = [float(s_off(x, y)) for _ in range(4)]
        s_on.drain()
        assert on == off
        assert det.incidents == [] and len(det.history) == 4

    def test_nan_batch_skips_update_and_detects_same_step(self):
        det = AnomalyDetector(policy="rollback", min_history=4)
        step = self._step(det)
        x, y = _mlp_data(0)
        l0 = float(step(x, y))
        params_before = [np.asarray(v) for v in step._param_vals]
        faults.arm("step.grads")  # poisons the float leaf -> NaN grads
        step(x, y)
        step.drain()
        # in-program skip: params/moments unchanged by the poisoned step
        for a, b in zip(params_before, step._param_vals):
            np.testing.assert_array_equal(a, np.asarray(b))
        assert det.pending is not None
        assert det.pending.kind == "nonfinite"
        # ... and the model still trains after clearing
        det.clear_pending()
        l2 = float(step(x, y))
        assert math.isfinite(l2) and l2 != l0

    def test_detector_property_and_flag_construction(self):
        set_flags({"anomaly_detection": True, "anomaly_policy": "warn"})
        try:
            step = self._step(None)  # None -> reads the flag
            assert step.anomaly_detector is not None
            assert step.anomaly_detector.policy == "warn"
        finally:
            set_flags({"anomaly_detection": False,
                       "anomaly_policy": "rollback"})
        assert self._step(None).anomaly_detector is None
        assert self._step(False).anomaly_detector is None


@pytest.mark.slow
class TestRunResilient:
    """Full supervisor recovery loops (compile-heavy: full tier; the quick
    tier keeps the registry/detector/step units, and the bench `resilience`
    arm drives the same recovery end-to-end)."""

    N = 24

    def _run(self, point=None, tmp=None, pol=None, **arm_kw):
        make_step, make_data = _make_step_factory(self.N)
        faults.reset()
        if point:
            faults.arm(point, **arm_kw)
        rep = run_resilient(make_step, make_data, self.N, str(tmp),
                            policy=pol, ckpt_every=6, feed_depth=2)
        faults.reset()
        return rep

    def test_fault_free_reference(self, tmp_path):
        rep = self._run(tmp=tmp_path)
        assert rep["status"] == "ok" and rep["rollbacks"] == 0
        assert len(rep["losses"]) == self.N
        assert all(math.isfinite(v) for v in rep["losses"].values())

    def test_nan_batch_rollback_bit_exact(self, tmp_path):
        ref = self._run(tmp=tmp_path / "ref")
        rep = self._run("step.grads", tmp=tmp_path / "chaos",
                        mode="nth", nth=10)
        assert rep["status"] == "ok" and rep["rollbacks"] == 1
        assert rep["losses"] == ref["losses"]  # bit-exact replay
        events = [e["event"] for e in rep["incidents"]]
        assert "anomaly" in events and "rollback" in events
        rb = next(e for e in rep["incidents"] if e["event"] == "rollback")
        assert rb["recovery_ms"] > 0

    def test_feeder_crash_resumes_at_cursor(self, tmp_path):
        ref = self._run(tmp=tmp_path / "ref")
        rep = self._run("feeder.collate", tmp=tmp_path / "chaos",
                        mode="nth", nth=13)
        assert rep["status"] == "ok" and rep["feeder_retries"] == 1
        assert rep["losses"] == ref["losses"]
        crash = next(e for e in rep["incidents"]
                     if e["event"] == "feeder_crash")
        assert crash["phase"] == "collate" and "FaultInjected" in crash["cause"]

    def test_killed_save_leaves_previous_committed(self, tmp_path):
        ref = self._run(tmp=tmp_path / "ref")
        rep = self._run("ckpt.before_rename", tmp=tmp_path / "chaos",
                        mode="nth", nth=2)
        assert rep["status"] == "ok" and rep["save_failures"] == 1
        assert rep["losses"] == ref["losses"]
        # the previous committed snapshot stayed loadable throughout
        mgr = elastic.CheckpointManager(str(tmp_path / "chaos"))
        assert mgr.latest() is not None
        mgr.load()

    def test_simulated_hang_restarts_from_hang_save(self, tmp_path):
        ref = self._run(tmp=tmp_path / "ref")
        rep = self._run("watchdog.hang", tmp=tmp_path / "chaos",
                        mode="nth", nth=15)
        assert rep["status"] == "ok" and rep["hang_restarts"] == 1
        assert rep["losses"] == ref["losses"]
        events = [e["event"] for e in rep["incidents"]]
        assert events.count("hang") == 1 and "restart" in events

    def test_persistent_fault_halts_with_report(self, tmp_path):
        """EVERY step poisoned: the run must end in a bounded, structured
        halt (rollback budget or no-older-snapshot), never a loop — with
        the incident report attached."""
        make_step, make_data = _make_step_factory(self.N)
        faults.reset()
        faults.arm("step.grads", mode="always")
        pol = ResiliencePolicy(max_rollbacks=2)
        with pytest.raises(ResilienceHalt) as ei:
            run_resilient(make_step, make_data, self.N, str(tmp_path),
                          policy=pol, ckpt_every=6, feed_depth=2)
        faults.reset()
        report = ei.value.report
        events = [e["event"] for e in report["incidents"]]
        assert "rollback" in events and "quarantine" in events
        assert report["rollbacks"] >= 1
        assert report["quarantined"]  # the recurring batch was quarantined

    def test_skip_batch_policy_quarantines(self, tmp_path):
        make_step, make_data = _make_step_factory(self.N)
        faults.reset()
        faults.arm("step.grads", mode="nth", nth=10)
        pol = ResiliencePolicy(anomaly="skip_batch")
        rep = run_resilient(make_step, make_data, self.N, str(tmp_path),
                            policy=pol, ckpt_every=6, feed_depth=2)
        faults.reset()
        assert rep["status"] == "ok" and rep["rollbacks"] == 0
        assert rep["quarantined"] == [9]  # nth=10 fires on step 10 = idx 9
        assert 9 not in rep["losses"]

    def test_skip_batch_continues_without_pipeline_rebuild(self, tmp_path):
        """skip_batch leaves params/step/cursor untouched (the in-program
        health skip already kept the poison out of the update), so the
        supervisor must continue the SAME input pipeline instead of
        tearing down and re-warming the feeder for every quarantined
        batch."""
        make_step, make_data = _make_step_factory(self.N)
        calls = []

        def counted_make_data(start):
            calls.append(start)
            return make_data(start)

        faults.reset()
        faults.arm("step.grads", mode="nth", nth=10)
        pol = ResiliencePolicy(anomaly="skip_batch")
        rep = run_resilient(make_step, counted_make_data, self.N,
                            str(tmp_path), policy=pol, ckpt_every=6,
                            feed_depth=2)
        faults.reset()
        assert rep["status"] == "ok" and rep["quarantined"] == [9]
        assert calls == [0]  # one pipeline for the whole run

    def test_caller_owned_incident_log_spans_runs(self, tmp_path):
        """run_resilient must not close a caller-provided IncidentLog: one
        log can span several runs (closing it would silently stop
        persisting the next run's events to the JSONL file)."""
        make_step, make_data = _make_step_factory(6)
        log = IncidentLog(str(tmp_path / "log.jsonl"))
        faults.reset()
        run_resilient(make_step, make_data, 6, str(tmp_path / "ck"),
                      ckpt_every=3, incident_log=log)
        assert log._f is not None  # still open for the next run
        log.emit("probe")
        log.close()
        lines = [json.loads(ln) for ln in open(tmp_path / "log.jsonl")]
        assert any(r["event"] == "probe" for r in lines)

    def test_incident_log_is_jsonl(self, tmp_path):
        make_step, make_data = _make_step_factory(self.N)
        log_path = str(tmp_path / "incidents.jsonl")
        faults.reset()
        faults.arm("step.grads", mode="nth", nth=10)
        rep = run_resilient(make_step, make_data, self.N,
                            str(tmp_path / "ck"), ckpt_every=6,
                            incident_log=log_path)
        faults.reset()
        lines = [json.loads(ln) for ln in open(log_path)]
        assert lines == rep["incidents"]
        for rec in lines:
            assert "ts" in rec and "event" in rec
        kinds = {r["event"] for r in lines}
        assert {"anomaly", "rollback"} <= kinds


class TestFitChaosMatrix:
    """The satellite chaos matrix: EVERY registered fault point injected
    once during a short Model.fit(auto_checkpoint=, resilience='rollback')
    run; training must complete with the fault-free per-batch loss
    trajectory (bit-exact — rollback replays the same batches from a
    bit-exact restore). Points whose sites a single-host fit never reaches
    (store.barrier, watchdog.hang) pass trivially here and are exercised
    by their dedicated tests above."""

    def _fit(self, point, ckpt_dir, arms=None, resilience="rollback",
             **fit_kw):
        set_mesh(None)
        build_mesh({"dp": 1})  # DistModel path: compiled step + DeviceFeeder
        paddle.seed(0)
        rng = np.random.RandomState(0)
        x = rng.randn(48, IN_DIM).astype(np.float32)
        y = rng.randint(0, N_CLS, (48,)).astype(np.int64)
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.model import Callback
        from paddle_tpu.io import TensorDataset

        class Rec(Callback):
            def __init__(self):
                self.losses = {}

            def on_epoch_begin(self, epoch, logs=None):
                self._e = epoch

            def on_train_batch_end(self, step, logs=None):
                if logs and "loss" in logs:
                    self.losses[(self._e, step)] = logs["loss"]

        net = nn.Sequential(nn.Linear(IN_DIM, 16), nn.ReLU(),
                            nn.Linear(16, N_CLS))
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=0.05,
                                            parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        faults.reset()
        if arms:
            for nm, nth in arms:
                faults.arm(nm, mode="nth", nth=nth)
        elif point is not None:
            # ckpt.* sites are hit once per SAVE (initial + 2 epoch ends):
            # nth=2 kills the epoch-0-end save; per-step sites fire mid-epoch
            faults.arm(point, mode="nth",
                       nth=2 if point.startswith("ckpt.") else 5)
        rec = Rec()
        ds = TensorDataset([x, y])
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                model.fit(ds, batch_size=8, epochs=2, verbose=0,
                          shuffle=False, auto_checkpoint=str(ckpt_dir),
                          resilience=resilience, callbacks=[rec], **fit_kw)
        finally:
            faults.reset()
        return rec.losses

    def test_chaos_matrix_covers_registry(self):
        # serving points register at import of the serving modules; pull
        # them in so the pin is deterministic whether or not another test
        # module imported paddle_tpu.serving first
        import paddle_tpu.serving.disagg  # noqa: F401
        import paddle_tpu.serving.replica  # noqa: F401
        import paddle_tpu.serving.router  # noqa: F401
        assert (sorted(CHAOS_POINTS + SERVING_CHAOS_POINTS)
                == sorted(faults.registered())), (
            "a fault point was registered without being added to a chaos "
            "matrix (CHAOS_POINTS here, SERVING_CHAOS_POINTS -> "
            "test_router.py / test_disagg.py)")

    @pytest.mark.slow
    def test_every_point_recovers_with_fault_free_trajectory(self, tmp_path):
        ref = self._fit(None, tmp_path / "ref")
        assert len(ref) == 12  # 6 batches x 2 epochs
        failures = []
        for i, point in enumerate(CHAOS_POINTS):
            got = self._fit(point, tmp_path / f"c{i}")
            if got != ref:
                failures.append((point, {k: (ref[k], got.get(k))
                                         for k in ref if ref[k] != got.get(k)}))
        assert not failures, failures

    def test_last_batch_anomaly_settles_before_fit_returns(self, tmp_path):
        """The run-ahead window must settle at epoch end: an anomaly on the
        FINAL dispatched batches (whose health buffers after_batch hadn't
        read yet) cannot escape the epoch — with policy 'halt' the fit must
        raise, not return a silently poisoned model."""
        with pytest.raises(RuntimeError, match="halt"):
            # nth=12 poisons the very last step (6 batches x 2 epochs);
            # metrics_sync_every=4 keeps the tail steps' losses deferred
            self._fit(None, tmp_path, arms=[("step.grads", 12)],
                      resilience="halt", metrics_sync_every=4)

    def test_rollback_across_epoch_boundary_replays_gap(self, tmp_path):
        """A rollback whose newest committed snapshot predates the current
        epoch (here: the epoch-0-end save was killed and swallowed as a
        resilient incident) must re-enter the epoch loop at the SNAPSHOT's
        epoch — replaying the batches between it and the anomaly instead of
        silently dropping them from training."""
        ref = self._fit(None, tmp_path / "ref")
        got = self._fit(None, tmp_path / "chaos",
                        arms=[("ckpt.before_rename", 2),  # epoch-0-end save
                              ("step.grads", 8)])         # epoch 1, step 1
        assert got == ref  # bit-exact: both epochs replayed from step 0

    def test_shuffled_loader_warns_about_positional_replay(self, tmp_path):
        """Replay/quarantine are positional; the default shuffle=True
        silently breaks the bit-exact contract — fit must say so."""
        set_mesh(None)
        build_mesh({"dp": 1})
        paddle.seed(0)
        from paddle_tpu.hapi import Model
        from paddle_tpu.io import TensorDataset

        net = nn.Linear(IN_DIM, N_CLS)
        model = Model(net)
        model.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        x, y = _mlp_data(0, batch=16)
        with pytest.warns(UserWarning, match="BY POSITION"):
            model.fit(TensorDataset([x, y]), batch_size=8, epochs=1,
                      verbose=0, shuffle=True,
                      auto_checkpoint=str(tmp_path), resilience="rollback")

    def test_rollback_policy_requires_auto_checkpoint(self):
        set_mesh(None)
        from paddle_tpu.hapi import Model
        from paddle_tpu.io import TensorDataset

        net = nn.Linear(IN_DIM, N_CLS)
        model = Model(net)
        model.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        x, y = _mlp_data(0)
        with pytest.raises(ValueError, match="auto_checkpoint"):
            model.fit(TensorDataset([x, y]), batch_size=4, epochs=1,
                      verbose=0, resilience="rollback")


class TestGradScalerSkipStreak:
    def test_warn_then_halt_and_reset(self):
        from paddle_tpu.amp import GradScaler

        set_flags({"scaler_max_consecutive_skips": 4})
        try:
            s = GradScaler(init_loss_scaling=8.0)
            s._found_inf = True
            with pytest.warns(UserWarning, match="consecutive"):
                s.update()  # streak 1... warn fires at limit//2 = 2
                s._found_inf = True
                s.update()
            # a good step resets the streak
            s._found_inf = False
            s.update()
            assert s._consecutive_skips == 0
            for _ in range(3):
                s._found_inf = True
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    s.update()
            s._found_inf = True
            with pytest.raises(FloatingPointError,
                               match="scaler_max_consecutive_skips"):
                s.update()
        finally:
            set_flags({"scaler_max_consecutive_skips": 100})

    def test_zero_disables(self):
        from paddle_tpu.amp import GradScaler

        set_flags({"scaler_max_consecutive_skips": 0})
        try:
            s = GradScaler(init_loss_scaling=8.0)
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # any warning would raise
                for _ in range(20):
                    s._found_inf = True
                    s.update()
        finally:
            set_flags({"scaler_max_consecutive_skips": 100})

    def test_compiled_step_streak_halts(self):
        """e2e: a permanently-NaN model under the compiled GradScaler path
        halts instead of skipping forever."""
        from paddle_tpu.amp import GradScaler

        set_flags({"scaler_max_consecutive_skips": 3})
        try:
            paddle.seed(7)
            net = nn.Linear(IN_DIM, N_CLS)
            # poison the weights: every step's grads are NaN from here on
            net.weight._set_value(net.weight._value * float("nan"))
            opt = paddle.optimizer.Adam(learning_rate=0.05,
                                        parameters=net.parameters())
            crit = nn.CrossEntropyLoss()
            step = CompiledTrainStep(net, lambda o, l: crit(o, l), opt,
                                     grad_scaler=GradScaler(
                                         init_loss_scaling=8.0))
            x, y = _mlp_data(0)
            with pytest.raises(FloatingPointError, match="permanently NaN"):
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    for _ in range(8):
                        step(x, y)
                        step.drain()
        finally:
            set_flags({"scaler_max_consecutive_skips": 100})


class TestWatchdogThreadStacks:
    def test_diagnostics_name_where_threads_block(self):
        from paddle_tpu.distributed import watchdog

        gate = threading.Event()

        def blocked_in_a_named_place():
            gate.wait(10.0)

        t = threading.Thread(target=blocked_in_a_named_place,
                             name="stuck-worker", daemon=True)
        t.start()
        time.sleep(0.05)
        try:
            diag = watchdog.CommTaskManager().diagnostics()
            assert "threads" in diag
            mine = next(th for th in diag["threads"]
                        if th["name"] == "stuck-worker")
            joined = "\n".join(mine["stack"])
            # the dump names WHERE the thread is blocked
            assert "blocked_in_a_named_place" in joined
            assert "wait" in joined
        finally:
            gate.set()
            t.join(5)

    def test_hang_report_carries_stacks(self):
        from paddle_tpu.distributed import watchdog

        mgr = watchdog.CommTaskManager(default_timeout_s=0.1,
                                       poll_interval_s=0.02)
        seen = []
        off = watchdog.add_hang_listener(
            lambda task, diag: seen.append(diag), manager=mgr)

        class Stalled:
            def __array__(self, dtype=None):
                time.sleep(0.8)
                return np.zeros((), np.float32)

        try:
            watchdog.watch_step(Stalled(), name="stuck", timeout_s=0.1,
                                manager=mgr)
            deadline = time.time() + 5
            while not seen and time.time() < deadline:
                time.sleep(0.02)
            assert seen and "threads" in seen[0]
            assert any(th["stack"] for th in seen[0]["threads"])
        finally:
            off()
            mgr.stop()


class TestFeederCrashContext:
    def _src(self, n=6):
        for i in range(n):
            yield (np.full((2, 2), i, np.float32),)

    @pytest.mark.parametrize("point,phase", [("feeder.collate", "collate"),
                                             ("feeder.device_put",
                                              "device_put")])
    def test_crash_carries_cursor_and_phase(self, point, phase):
        faults.reset()
        faults.arm(point, mode="nth", nth=3)
        feeder = DeviceFeeder(self._src(), mesh=None, depth=2)
        got = []
        with pytest.raises(FeederWorkerError) as ei:
            for b in feeder:
                got.append(b)
        err = ei.value
        assert err.phase == phase
        assert err.batch_index == 2  # third batch (0-based) was in flight
        assert isinstance(err.__cause__, faults.FaultInjected)
        assert str(err.batch_index) in str(err) and phase in str(err)
        # batches before the crash were delivered; shutdown is clean
        assert len(got) == 2
        assert not feeder._thread.is_alive()
        faults.reset()

    def test_crash_with_full_queue_never_deadlocks_shutdown(self):
        """Worker dies while the bounded queue is FULL and the consumer
        stops reading: close() must drain and join without hanging."""
        faults.reset()
        faults.arm("feeder.collate", mode="nth", nth=4)
        feeder = DeviceFeeder(self._src(20), mesh=None, depth=2)
        next(feeder)  # consume one, then abandon the iterator
        time.sleep(0.2)  # let the worker fill the queue and crash
        t0 = time.time()
        feeder.close()
        assert time.time() - t0 < 2.0
        assert not feeder._thread.is_alive()
        faults.reset()


class TestStoreHardening:
    def test_barrier_retry_absorbs_transient_fault(self):
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True)
        try:
            faults.reset()
            faults.arm("store.barrier")  # one-shot: first attempt fails
            store.barrier("rb", world_size=1, timeout=5.0, rank=0,
                          retries=2, retry_backoff=0.01)
            assert faults.fired("store.barrier") == 1
        finally:
            faults.reset()
            store.close()

    def test_barrier_timeout_reports_attempts_and_ranks(self):
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True)
        try:
            with pytest.raises(TimeoutError) as ei:
                store.barrier("rb2", world_size=3, timeout=0.15, rank=0,
                              retries=1, retry_backoff=0.01)
            msg = str(ei.value)
            assert "2 attempt(s)" in msg
            assert "1/3 ranks arrived" in msg
            assert "missing ranks [1, 2]" in msg
        finally:
            store.close()

    def test_heartbeat_names_dead_and_live_ranks(self):
        from paddle_tpu.distributed.store import (RankHeartbeat, TCPStore,
                                                  dead_peers)

        store = TCPStore(is_master=True)
        hb = RankHeartbeat(store, "job", rank=0, interval_s=0.05)
        try:
            deadline = time.time() + 3
            while hb.beats == 0 and time.time() < deadline:
                time.sleep(0.01)
            # rank 0 beats; rank 1 never showed up
            dead = dead_peers(store, "job", world_size=2, timeout_s=10.0)
            assert dead == [{"rank": 1, "age_s": None}]
            # die WITHOUT the clean-exit tombstone: rank 0 goes stale and
            # is NAMED, with its staleness age
            hb.stop(mark_clean=False)
            time.sleep(0.12)
            dead = dead_peers(store, "job", world_size=2, timeout_s=0.1)
            ranks = [d["rank"] for d in dead]
            assert ranks == [0, 1]
            assert dead[0]["age_s"] is not None and dead[0]["age_s"] > 0.1
        finally:
            hb.stop()
            store.close()

    def test_heartbeat_clean_stop_is_not_a_corpse(self):
        from paddle_tpu.distributed.store import (RankHeartbeat, TCPStore,
                                                  dead_peers)

        store = TCPStore(is_master=True)
        try:
            hb = RankHeartbeat(store, "job2", rank=0, interval_s=0.05)
            deadline = time.time() + 3
            while hb.beats == 0 and time.time() < deadline:
                time.sleep(0.01)
            hb.stop()  # writes the +inf tombstone: a clean exit...
            time.sleep(0.12)
            dead = dead_peers(store, "job2", world_size=1, timeout_s=0.05)
            assert dead == []  # ...is never reported dead, even when stale
        finally:
            store.close()

    def test_dead_peers_watch_is_clock_skew_immune(self):
        """On a real pod the beat payload is the REMOTE host's wall clock;
        with `watch`, staleness is local time since the value last CHANGED,
        so an NTP-skewed peer neither reads as a permanent corpse (clock
        behind) nor masks a real death (clock ahead)."""
        import struct as _struct

        from paddle_tpu.distributed.store import TCPStore, dead_peers

        store = TCPStore(is_master=True)
        key = "__hb__/skew/0"
        try:
            # a peer whose clock lags by ~1h: the stateless comparison
            # names a live, beating rank as a corpse...
            store.set(key, _struct.pack("<d", time.time() - 3600.0))
            assert [d["rank"] for d in
                    dead_peers(store, "skew", 1, timeout_s=10.0)] == [0]
            # ...but a watch dict sees the VALUE move: alive
            watch = {}
            dead_peers(store, "skew", 1, timeout_s=0.1, watch=watch)
            store.set(key, _struct.pack("<d", time.time() - 3599.0))
            time.sleep(0.15)
            assert dead_peers(store, "skew", 1, timeout_s=0.1,
                              watch=watch) == []
            # frozen value: after timeout_s of LOCAL time it IS a corpse,
            # even though its future-dated stamp still looks fresh...
            store.set(key, _struct.pack("<d", time.time() + 3600.0))
            dead_peers(store, "skew", 1, timeout_s=0.1, watch=watch)
            time.sleep(0.15)
            assert [d["rank"] for d in
                    dead_peers(store, "skew", 1, timeout_s=0.1,
                               watch=watch)] == [0]
            # ...a corpse the stateless comparison masks entirely
            assert dead_peers(store, "skew", 1, timeout_s=10.0) == []
        finally:
            store.close()


class TestExceptPassLint:
    """Tier-1 lint: a bare `except ...: pass` swallows the very failures
    the resilience layer exists to surface. Every handler whose body is
    exactly `pass` must be allowlisted (tools/except_pass_allowlist.txt)
    with the file + except-line — so new swallowing shows up in review."""

    ALLOWLIST = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                             "except_pass_allowlist.txt")

    def _offenders(self):
        import ast

        import paddle_tpu

        root = os.path.dirname(paddle_tpu.__file__)
        repo = os.path.dirname(root)
        out = set()
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in files:
                if not f.endswith(".py"):
                    continue
                p = os.path.join(dirpath, f)
                src = open(p).read()
                try:
                    tree = ast.parse(src)
                except SyntaxError:
                    continue
                lines = src.splitlines()
                rel = os.path.relpath(p, repo)
                for node in ast.walk(tree):
                    if (isinstance(node, ast.ExceptHandler)
                            and len(node.body) == 1
                            and isinstance(node.body[0], ast.Pass)):
                        out.add(f"{rel} :: "
                                f"{lines[node.lineno - 1].strip()}")
        return out

    def test_no_unallowlisted_exception_swallowing(self):
        allow = set()
        with open(self.ALLOWLIST) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    allow.add(line)
        offenders = self._offenders()
        new = sorted(offenders - allow)
        assert not new, (
            "new `except ...: pass` swallowing (handle the error, report "
            "it, or add a reviewed entry to tools/except_pass_allowlist"
            ".txt):\n" + "\n".join(new))
        stale = sorted(allow - offenders)
        assert not stale, (
            "stale allowlist entries (the handler was fixed/moved — prune "
            "them so the allowlist stays honest):\n" + "\n".join(stale))


class TestRegistryCoverage:
    def test_every_registered_point_is_exercised_by_tests(self):
        """Every registered fault point must appear (as a literal) in at
        least one test module — an injection point nobody chaos-tests is
        dead weight that will silently rot."""
        # the site modules register at import; make sure they're all in
        import paddle_tpu.distributed.checkpoint.elastic  # noqa: F401
        import paddle_tpu.distributed.resilience.supervisor  # noqa: F401
        import paddle_tpu.distributed.store  # noqa: F401
        import paddle_tpu.io.device_feed  # noqa: F401
        import paddle_tpu.parallel.train_step  # noqa: F401
        import paddle_tpu.serving.replica  # noqa: F401
        import paddle_tpu.serving.router  # noqa: F401

        tests_dir = os.path.dirname(__file__)
        corpus = ""
        for f in os.listdir(tests_dir):
            if f.endswith(".py"):
                corpus += open(os.path.join(tests_dir, f)).read()
        uncovered = [p for p in faults.registered() if p not in corpus]
        assert not uncovered, (
            f"registered fault points never exercised by any test: "
            f"{uncovered}")
