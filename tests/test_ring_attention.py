"""Ring attention (sep-axis context parallelism) vs dense attention."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.parallel.ring_attention import ring_attention


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_rep=False)


def _dense(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal):
    mesh = build_mesh({"sep": 4})
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 2, 16
    q, k, v = [jnp.asarray(rng.randn(B, S, H, D), jnp.float32) for _ in range(3)]

    spec = PartitionSpec(None, "sep")
    fn = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=causal),
        mesh, (spec, spec, spec), spec,
    )
    out = jax.jit(fn)(q, k, v)
    ref = _dense(q, k, v, causal)
    set_mesh(None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_ring_attention_grads_flow():
    mesh = build_mesh({"sep": 4})
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 16, 1, 8
    q, k, v = [jnp.asarray(rng.randn(B, S, H, D), jnp.float32) for _ in range(3)]
    spec = PartitionSpec(None, "sep")
    fn = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True),
        mesh, (spec, spec, spec), spec,
    )

    def loss(q, k, v):
        return fn(q, k, v).sum()

    def ref_loss(q, k, v):
        return _dense(q, k, v, True).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    set_mesh(None)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


def test_chunked_block_path_matches_unchunked():
    """The Q-chunked tiling inside _block_attn must be numerically identical
    to the single-chunk path (and keep causal masking exact)."""
    import jax.numpy as jnp

    from paddle_tpu.parallel.ring_attention import _block_attn

    rng = np.random.RandomState(0)
    B, Sq, Sk, H, D = 2, 8, 8, 2, 4
    q = jnp.asarray(rng.randn(B, Sq, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, Sk, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, Sk, H, D), jnp.float32)
    qpos = jnp.arange(Sq)
    kpos = jnp.arange(Sk)
    a1, m1, l1 = _block_attn(q, k, v, qpos, kpos, 0.5, True, q_chunk=Sq)
    a2, m2, l2 = _block_attn(q, k, v, qpos, kpos, 0.5, True, q_chunk=2)
    np.testing.assert_allclose(np.asarray(a2), np.asarray(a1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(l2), np.asarray(l1), rtol=1e-6)
    # non-multiple chunk: ceil tiling with a padded remainder, sliced back
    a3, m3, l3 = _block_attn(q, k, v, qpos, kpos, 0.5, True, q_chunk=3)
    np.testing.assert_allclose(np.asarray(a3), np.asarray(a1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(l3), np.asarray(l1), rtol=1e-6)


def test_context_parallel_training_matches_dense():
    """TRAIN a toy attention model with the sequence sharded over sep=4 and
    ring attention doing the cross-shard work: losses and final weights must
    track the dense (single-device-attention) run step for step."""
    mesh = build_mesh({"sep": 4})
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 2, 8
    x = jnp.asarray(rng.randn(B, S, H * D), jnp.float32)
    tgt = jnp.asarray(rng.randn(B, S, H * D), jnp.float32)
    w0 = jnp.asarray(rng.randn(H * D, H * D) * 0.2, jnp.float32)

    spec = PartitionSpec(None, "sep")

    def model(w, xv, attn_fn):
        qkv = xv @ w
        q = qkv.reshape(B, S, H, D)
        out = attn_fn(q, q, q)
        return out.reshape(B, S, H * D)

    def loss_dense(w):
        out = model(w, x, lambda a, b, c: _dense(a, b, c, True))
        return jnp.mean((out - tgt) ** 2)

    ring_fn = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True),
        mesh, (spec, spec, spec), spec)

    def loss_ring(w):
        out = model(w, x, ring_fn)
        return jnp.mean((out - tgt) ** 2)

    gd = jax.jit(jax.value_and_grad(loss_dense))
    gr = jax.jit(jax.value_and_grad(loss_ring))
    wd = wr = w0
    for _ in range(5):
        ld, grad_d = gd(wd)
        lr_, grad_r = gr(wr)
        np.testing.assert_allclose(float(lr_), float(ld), rtol=1e-5)
        wd = wd - 0.1 * grad_d
        wr = wr - 0.1 * grad_r
    np.testing.assert_allclose(np.asarray(wr), np.asarray(wd),
                               rtol=1e-4, atol=1e-5)
    set_mesh(None)
