"""Fleet-front router (PR 11): rendezvous placement, circuit breaking,
draining, bounded failover, admission/shed degradation, the serving chaos
matrix over every `serving.*` fault point, and the routed-vs-direct
acceptance checks.

Most tests run against `FakeEngine` — the REAL ContinuousBatchingScheduler
+ PageAllocator (admission, QueueFull pushback, eviction re-queues, cancel/
release bookkeeping) around a deterministic token function instead of a
compiled decode program — so router behavior is exercised on the true
scheduling machinery without per-engine XLA compiles. The token function
depends only on (prompt, index), the same property the PR-9
eviction-equivalence contract proves for greedy decoding, so a failover
re-prefill on a peer MUST reproduce the exact stream. One class at the end
routes a real ServingEngine for the zero-decode-retrace + greedy-parity
acceptance criteria.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.resilience import faults
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.serving import (ContinuousBatchingScheduler, PageAllocator,
                                QueueFull, Request)
from paddle_tpu.serving.replica import (InProcessReplica, ReplicaDead,
                                        ReplicaError, StreamCut)
from paddle_tpu.serving.router import (Router, RouterConfig, _Dispatch,
                                       backoff_delays, rendezvous_order)

# serving.* fault points as LITERALS (the registry-coverage lint greps for
# them; the routed chaos matrix below injects each one)
SERVING_POINTS = ["serving.replica.kill", "serving.replica.slow",
                  "serving.dispatch.drop", "serving.stream.cut"]


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------
class FakeEngine:
    """Host-only ServingEngine stand-in behind the transport seam: real
    scheduler + allocator, deterministic tokens, optional per-step delay
    so streams have duration (failure windows exist mid-stream)."""

    def __init__(self, num_pages=64, page_size=4, max_seq_len=64,
                 max_waiting=0, decode_batch=4, step_delay_s=0.0):
        self.decode_batch = decode_batch
        self.allocator = PageAllocator(num_pages, page_size)
        self.scheduler = ContinuousBatchingScheduler(
            self.allocator, decode_batch, max_seq_len,
            max_waiting=max_waiting)
        self.step_delay_s = step_delay_s
        self.steps = 0
        self.decode_retraces_after_warmup = 0

    @staticmethod
    def token(prompt, i):
        """Deterministic greedy stand-in: depends ONLY on (prompt, index),
        so any replica — and any post-eviction/failover re-prefill —
        produces the identical stream."""
        return (int(np.sum(np.asarray(prompt, np.int64))) * 31 + 7 * i) % 997

    def submit(self, prompt, max_new_tokens=16, temperature=0.0, top_k=0,
               top_p=1.0, eos_id=None, stream_cb=None):
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), eos_id=eos_id, stream_cb=stream_cb)
        return self.scheduler.submit(req)

    def step(self):
        if self.step_delay_s:
            time.sleep(self.step_delay_s)
        for req in self.scheduler.admissions():
            self.scheduler.activate(req)
        self.scheduler.grow()
        self.steps += 1
        for req in list(self.scheduler.running):
            tok = self.token(req.prompt, len(req.generated))
            req.generated.append(tok)
            req.token_times.append(time.perf_counter())
            if req.stream_cb is not None:
                req.stream_cb(req, tok)
            if ((req.eos_id is not None and tok == req.eos_id)
                    or len(req.generated) >= req.max_new_tokens):
                self.scheduler.finish(req)
        return bool(self.scheduler.running)

    def cancel(self, rid):
        return self.scheduler.cancel(rid)

    def release(self, rid):
        self.scheduler.release(rid)

    def stats(self):
        running = len(self.scheduler.running)
        return {"queue_depth": self.scheduler.queue_depth,
                "oldest_wait_age_s": self.scheduler.oldest_wait_age(),
                "in_flight": running + self.scheduler.queue_depth,
                "slot_fill": running / max(self.decode_batch, 1),
                "decode_retraces_after_warmup": 0,
                "free_pages": self.allocator.free_pages,
                "waiting_limit": self.scheduler.max_waiting}


def _expected(prompt, n):
    return [FakeEngine.token(prompt, i) for i in range(n)]


class ScriptedStream:
    def __init__(self, events):
        self._events = list(events)
        self.closed = False

    def next_event(self, timeout_s):
        if not self._events:
            time.sleep(min(timeout_s, 0.005))
            return None                      # silence (gap accounting)
        ev = self._events.pop(0)
        if isinstance(ev, Exception):
            raise ev
        if ev is None:
            time.sleep(min(timeout_s, 0.005))
            return None
        return ev

    def close(self):
        self.closed = True


class ScriptedReplica:
    """Pure-transport fake for router unit tests: scripted probe results
    and stream factories, with every payload/handle recorded."""

    def __init__(self, rid, stream_factory=None):
        self.replica_id = rid
        self.probe_result = {"ok": True, "queue_depth": 0, "slot_fill": 0.0}
        self.probe_exc = None
        self.stream_factory = stream_factory
        self.payloads = []
        self.handles = []

    def probe(self):
        if self.probe_exc is not None:
            raise self.probe_exc
        return dict(self.probe_result)

    def open_stream(self, payload):
        self.payloads.append(dict(payload))
        if self.stream_factory is not None:
            h = self.stream_factory(payload)
        else:
            toks = _expected(payload["prompt_ids"],
                             int(payload.get("max_new_tokens", 16)))
            h = ScriptedStream([{"token": t} for t in toks]
                               + [{"done": True}])
        self.handles.append(h)
        return h


def _cfg(**over):
    base = dict(probe_interval_s=0.01, failure_threshold=3,
                breaker_cooldown_s=0.05, dispatch_attempts=3,
                backoff_initial_s=0.005, backoff_max_s=0.02,
                gap_timeout_s=0.3, max_inflight=8, shed_queue_depth=10_000,
                shed_max_new_tokens=2, retry_after_s=0.25)
    base.update(over)
    return RouterConfig(**base)


def _payload(prompt, n=5, **kw):
    return {"prompt_ids": [int(t) for t in np.asarray(prompt).ravel()],
            "max_new_tokens": n, **kw}


# ---------------------------------------------------------------------------
# placement primitives
# ---------------------------------------------------------------------------
class TestRendezvous:
    def test_remap_minimality_on_removal_and_addition(self):
        ids = [0, 1, 2, 3]
        keys = [f"session-{i}" for i in range(200)]
        first = {k: rendezvous_order(k, ids)[0] for k in keys}
        # every replica owns a share (no degenerate hash)
        assert set(first.values()) == set(ids)
        # removing id 2 remaps ONLY the keys that ranked it first
        for k in keys:
            f2 = rendezvous_order(k, [0, 1, 3])[0]
            if first[k] != 2:
                assert f2 == first[k], k
            else:
                assert f2 in (0, 1, 3)
        # adding id 4 steals ONLY the keys that now rank it first
        for k in keys:
            f3 = rendezvous_order(k, ids + [4])[0]
            if f3 != 4:
                assert f3 == first[k], k

    def test_order_is_deterministic_permutation(self):
        ids = [5, 9, 2]
        o1 = rendezvous_order("k", ids)
        assert o1 == rendezvous_order("k", [9, 2, 5])
        assert sorted(o1) == sorted(ids)

    def test_backoff_delays_double_and_cap(self):
        assert backoff_delays(4, 0.05, 0.15) == [0.05, 0.1, 0.15]
        assert backoff_delays(3, 0.1, 10.0) == [0.1, 0.2]
        assert backoff_delays(1, 0.1, 1.0) == []


# ---------------------------------------------------------------------------
# circuit breaker + drain
# ---------------------------------------------------------------------------
class TestBreaker:
    def test_probe_failures_trip_after_threshold(self):
        a, b = ScriptedReplica(0), ScriptedReplica(1)
        r = Router([a, b], _cfg(), start_monitor=False)
        try:
            a.probe_exc = ReplicaError("probe down")
            for i in range(3):
                r.monitor_tick()
                want_open = i >= 2                # threshold = 3
                assert (r.stats()["replicas"]["0"]["circuit"]
                        == ("open" if want_open else "closed"))
            h = r.health()
            assert h["ok"] and h["healthy"] == [1]
        finally:
            r.close()

    def test_half_open_trial_reopens_then_closes(self):
        a, b = ScriptedReplica(0), ScriptedReplica(1)
        r = Router([a, b], _cfg(breaker_cooldown_s=0.03),
                   start_monitor=False)
        try:
            a.probe_exc = ReplicaError("down")
            for _ in range(3):
                r.monitor_tick()
            assert r.stats()["replicas"]["0"]["trips"] == 1
            r.monitor_tick()          # still cooling: no trial, still open
            assert r.stats()["replicas"]["0"]["circuit"] == "open"
            time.sleep(0.04)
            r.monitor_tick()          # half-open trial fails -> re-open
            s = r.stats()["replicas"]["0"]
            assert s["circuit"] == "open" and s["trips"] == 2
            assert "half-open" in s["last_cause"]
            time.sleep(0.04)
            a.probe_exc = None
            r.monitor_tick()          # trial succeeds -> closed
            s = r.stats()["replicas"]["0"]
            assert s["circuit"] == "closed"
            assert s["consecutive_failures"] == 0
        finally:
            r.close()

    def test_dispatch_failures_count_toward_breaker(self):
        def boom(payload):
            raise ReplicaError("dispatch refused")

        a = ScriptedReplica(0, stream_factory=boom)
        b = ScriptedReplica(1)
        r = Router([a, b], _cfg(failure_threshold=2), start_monitor=False)
        try:
            p = np.arange(1, 5)
            for _ in range(2):        # ties go to the lowest rid -> a first
                toks, term = r.generate(_payload(p))
                assert term["done"] and term["failovers"] == 1
                assert toks == _expected(p, 5)
            s = r.stats()["replicas"]["0"]
            assert s["circuit"] == "open" and s["trips"] == 1
            # an OPEN circuit is skipped entirely: no third strike, no retry
            toks, term = r.generate(_payload(p))
            assert term["done"] and term["failovers"] == 0
            assert term["replica"] == 1
            assert len(a.payloads) == 2
        finally:
            r.close()

    def test_trip_drains_inflight_oldest_first(self):
        a, b = ScriptedReplica(0), ScriptedReplica(1)
        r = Router([a, b], _cfg(), start_monitor=False)
        try:
            # white-box: synthesize in-flight dispatches bound to each slot
            ctxs = {}
            for seq, (rid, at) in enumerate([(0, 3.0), (0, 1.0), (1, 0.5),
                                             (0, 2.0)]):
                c = _Dispatch(seq=seq, arrival_t=at, abort=threading.Event())
                c.replica_id = rid
                r._inflight[seq] = c
                ctxs[seq] = c
            seqs = r.drain(0, why="maintenance")
            assert seqs == [1, 3, 0]   # replica-0 dispatches, arrival order
            assert all(ctxs[s].abort.is_set() for s in seqs)
            assert ctxs[2].abort.is_set() is False     # replica 1 untouched
            assert all(ctxs[s].abort_why == "maintenance" for s in seqs)
            assert r.stats()["replicas"]["0"]["draining"] is True
            assert r.stats()["drained"] == 3
            # draining replicas take no new placements until undrain
            assert r._pick(None, ()).rid == 1
            r.undrain(0)
            assert r._pick(None, ()).rid in (0, 1)
        finally:
            r._inflight.clear()
            r.close()


# ---------------------------------------------------------------------------
# placement, admission, degradation
# ---------------------------------------------------------------------------
class TestPlacement:
    def test_session_affinity_and_minimal_remap_on_trip(self):
        reps = [ScriptedReplica(i) for i in range(3)]
        r = Router(reps, _cfg(), start_monitor=False)
        try:
            key = "user-42"
            home = rendezvous_order(key, [0, 1, 2])[0]
            p = np.arange(1, 6)
            for _ in range(3):        # sticky across calls
                toks, term = r.generate(_payload(p, session=key))
                assert term["replica"] == home
            reps[home].probe_exc = ReplicaError("down")
            for _ in range(3):
                r.monitor_tick()
            alive = [i for i in range(3) if i != home]
            toks, term = r.generate(_payload(p, session=key))
            assert term["replica"] == rendezvous_order(key, alive)[0]
            # an unkeyed session elsewhere is unaffected by the remap
            assert toks == _expected(p, 5)
        finally:
            r.close()

    def test_unkeyed_goes_to_least_loaded(self):
        a, b = ScriptedReplica(0), ScriptedReplica(1)
        a.probe_result = {"ok": True, "queue_depth": 7, "slot_fill": 1.0}
        r = Router([a, b], _cfg(), start_monitor=False)
        try:
            r.monitor_tick()          # load the probe views
            toks, term = r.generate(_payload(np.arange(1, 4)))
            assert term["replica"] == 1
        finally:
            r.close()

    def test_admission_refuses_past_max_inflight(self):
        a = ScriptedReplica(0)
        r = Router([a], _cfg(max_inflight=2), start_monitor=False)
        try:
            for seq in (91, 92):      # white-box: saturate the in-flight cap
                c = _Dispatch(seq=seq, arrival_t=0.0,
                              abort=threading.Event())
                r._inflight[seq] = c
            rej = r.admission_check({"prompt_ids": [1]})
            assert rej["status"] == 503
            assert rej["retry_after"] == pytest.approx(0.25)
            toks, term = r.generate(_payload(np.arange(1, 3)))
            assert toks == [] and term["error"] == "refused"
            assert term["retry_after"] == pytest.approx(0.25)
            assert r.stats()["refused"] == 2
            r._inflight.clear()
            assert r.admission_check({"prompt_ids": [1]}) is None
        finally:
            r._inflight.clear()
            r.close()

    def test_admission_refuses_with_no_healthy_replica(self):
        a = ScriptedReplica(0)
        r = Router([a], _cfg(), start_monitor=False)
        try:
            a.probe_exc = ReplicaError("down")
            for _ in range(3):
                r.monitor_tick()
            rej = r.admission_check({"prompt_ids": [1]})
            assert rej["status"] == 503 and "healthy" in rej["message"]
            assert r.health()["ok"] is False
        finally:
            r.close()

    def test_shed_caps_max_new_tokens_before_dropping(self):
        a = ScriptedReplica(0)
        r = Router([a], _cfg(shed_queue_depth=0, shed_max_new_tokens=2),
                   start_monitor=False)
        try:
            p = np.arange(1, 7)
            toks, term = r.generate(_payload(p, n=10))
            assert term["done"] and term.get("shed") is True
            assert a.payloads[0]["max_new_tokens"] == 2
            assert toks == _expected(p, 2)     # degraded, not dropped
            assert r.stats()["sheds"] == 1
            # under the watermark no shed: raise it and re-check
            r.cfg.shed_queue_depth = 10_000
            toks, term = r.generate(_payload(p, n=4))
            assert "shed" not in term and toks == _expected(p, 4)
        finally:
            r.close()

    def test_queue_full_excludes_without_breaker_strike(self):
        def full(payload):
            raise QueueFull(5, 5)

        a = ScriptedReplica(0, stream_factory=full)
        b = ScriptedReplica(1)
        r = Router([a, b], _cfg(), start_monitor=False)
        try:
            p = np.arange(2, 6)
            toks, term = r.generate(_payload(p))
            assert term["done"] and term["replica"] == 1
            assert term["failovers"] == 1
            assert toks == _expected(p, 5)
            s = r.stats()["replicas"]["0"]
            assert s["circuit"] == "closed"
            assert s["consecutive_failures"] == 0     # pushback != illness
        finally:
            r.close()

    def test_all_replicas_queue_full_maps_to_503_retry_after(self):
        def full(payload):
            raise QueueFull(5, 5)

        reps = [ScriptedReplica(i, stream_factory=full) for i in range(2)]
        r = Router(reps, _cfg(dispatch_attempts=2), start_monitor=False)
        try:
            toks, term = r.generate(_payload(np.arange(1, 4)))
            assert toks == [] and term["error"] == "queue_full"
            assert term["retry_after"] == pytest.approx(0.25)
            assert all(r.stats()["replicas"][str(i)]["circuit"] == "closed"
                       for i in range(2))
        finally:
            r.close()


# ---------------------------------------------------------------------------
# failover relay
# ---------------------------------------------------------------------------
class TestFailover:
    def test_mid_stream_cut_resumes_without_double_emit(self):
        p = np.arange(3, 9)
        want = _expected(p, 6)

        def cut_after_2(payload):
            toks = _expected(payload["prompt_ids"],
                             int(payload["max_new_tokens"]))
            return ScriptedStream([{"token": toks[0]}, {"token": toks[1]},
                                   StreamCut("connection died")])

        a = ScriptedReplica(0, stream_factory=cut_after_2)
        b = ScriptedReplica(1)
        r = Router([a, b], _cfg(), start_monitor=False)
        try:
            toks, term = r.generate(_payload(p, n=6))
            assert toks == want                    # each token EXACTLY once
            assert term["done"] and term["failovers"] == 1
            assert term["replica"] == 1
            assert a.handles[0].closed             # no leaked stream handle
            # the peer replayed from its own prefill: it was handed the
            # ORIGINAL prompt, not a resume cursor
            assert b.payloads[0]["prompt_ids"] == [int(t) for t in p]
            assert r._inflight == {}               # no per-request residue
        finally:
            r.close()

    def test_wedged_stream_fails_over_after_gap_timeout(self):
        a = ScriptedReplica(0, stream_factory=lambda p: ScriptedStream([]))
        b = ScriptedReplica(1)
        r = Router([a, b], _cfg(gap_timeout_s=0.1), start_monitor=False)
        try:
            p = np.arange(1, 5)
            t0 = time.monotonic()
            toks, term = r.generate(_payload(p))
            assert time.monotonic() - t0 >= 0.1    # silence cost the gap
            assert toks == _expected(p, 5)
            assert term["failovers"] == 1
            assert r.stats()["replicas"]["0"]["consecutive_failures"] == 1
        finally:
            r.close()

    def test_exhausted_attempts_yield_one_typed_error(self):
        def boom(payload):
            raise ReplicaError("always down")

        reps = [ScriptedReplica(i, stream_factory=boom) for i in range(4)]
        r = Router(reps, _cfg(dispatch_attempts=3, failure_threshold=99),
                   start_monitor=False)
        try:
            events = list(r.stream(_payload(np.arange(1, 4))))
            assert len(events) == 1                # exactly ONE terminal
            assert events[0]["error"] == "failover_exhausted"
            assert events[0]["failovers"] == 2
            assert r.stats()["failed"] == 1
        finally:
            r.close()

    def test_every_circuit_open_yields_typed_error(self):
        def boom(payload):
            raise ReplicaError("down")

        reps = [ScriptedReplica(i, stream_factory=boom) for i in range(2)]
        r = Router(reps, _cfg(dispatch_attempts=5), start_monitor=False)
        try:
            events = list(r.stream(_payload(np.arange(1, 4))))
            assert len(events) == 1
            assert events[0]["error"] == "no_healthy_replica"
            assert events[0]["retry_after"] == pytest.approx(0.25)
        finally:
            r.close()

    def test_deadline_yields_single_timeout_event(self):
        a = ScriptedReplica(0, stream_factory=lambda p: ScriptedStream([]))
        r = Router([a], _cfg(gap_timeout_s=5.0), start_monitor=False)
        try:
            t0 = time.monotonic()
            events = list(r.stream(_payload(np.arange(1, 4)),
                                   deadline=time.monotonic() + 0.08))
            assert [e.get("error") for e in events] == ["timeout"]
            assert 0.05 < time.monotonic() - t0 < 2.0
            assert a.handles[0].closed
        finally:
            r.close()

    def test_dispatch_drop_point_detected_within_gap_timeout(self):
        reps = [ScriptedReplica(i) for i in range(2)]
        r = Router(reps, _cfg(gap_timeout_s=0.08), start_monitor=False)
        try:
            faults.arm("serving.dispatch.drop")
            p = np.arange(4, 9)
            t0 = time.monotonic()
            toks, term = r.generate(_payload(p))
            assert time.monotonic() - t0 >= 0.08
            assert toks == _expected(p, 5)
            assert term["done"] and term["failovers"] == 1
            assert faults.fired("serving.dispatch.drop") == 1
        finally:
            faults.reset()
            r.close()


# ---------------------------------------------------------------------------
# the in-process replica transport (FakeEngine-backed)
# ---------------------------------------------------------------------------
class TestInProcessReplica:
    def test_probe_readiness_fields_and_stream_roundtrip(self):
        rep = InProcessReplica(FakeEngine(), replica_id=3)
        try:
            pr = rep.probe()
            for k in ("queue_depth", "oldest_wait_age_s", "slot_fill",
                      "decode_retraces_after_warmup", "free_pages"):
                assert k in pr, k
            assert pr["ok"] is True and pr["replica"] == 3
            p = np.arange(1, 6)
            h = rep.open_stream(_payload(p, n=4))
            toks, done = [], None
            while done is None:
                ev = h.next_event(1.0)
                if ev is None:
                    continue
                if "token" in ev:
                    toks.append(ev["token"])
                else:
                    done = ev
            h.close()
            assert toks == _expected(p, 4) and done["done"]
            # close released the engine-side bookkeeping
            assert rep.engine.scheduler._by_rid == {}
            assert rep.engine.allocator.used_pages == 0
        finally:
            rep.close()

    def test_kill_point_fails_probes_and_streams_fast(self):
        eng = FakeEngine()
        rep = InProcessReplica(eng, replica_id=0)
        try:
            faults.arm("serving.replica.kill")
            deadline = time.time() + 3.0
            while rep.dead_cause is None and time.time() < deadline:
                time.sleep(0.005)
            assert rep.dead_cause is not None
            with pytest.raises(ReplicaDead):
                rep.probe()
            with pytest.raises(ReplicaDead):
                rep.open_stream(_payload(np.arange(1, 3)))
            assert faults.fired("serving.replica.kill") == 1
        finally:
            faults.reset()
            rep.close()        # joins the (already-exited) driver thread

    def test_slow_point_degrades_without_killing(self):
        eng = FakeEngine()
        rep = InProcessReplica(eng, replica_id=0, slow_stall_s=0.05)
        try:
            faults.arm("serving.replica.slow")
            p = np.arange(2, 7)
            h = rep.open_stream(_payload(p, n=3))
            toks = []
            deadline = time.time() + 5.0
            while len(toks) < 3 and time.time() < deadline:
                ev = h.next_event(0.2)
                if ev and "token" in ev:
                    toks.append(ev["token"])
                elif ev and ev.get("done"):
                    break
            h.close()
            assert toks == _expected(p, 3)         # stalled, never wrong
            assert rep.dead_cause is None
            assert faults.fired("serving.replica.slow") == 1
        finally:
            faults.reset()
            rep.close()

    def test_stream_cut_point_raises_at_transport_seam(self):
        rep = InProcessReplica(FakeEngine(), replica_id=0)
        try:
            h = rep.open_stream(_payload(np.arange(1, 4), n=2))
            faults.arm("serving.stream.cut")
            with pytest.raises(StreamCut):
                for _ in range(50):
                    h.next_event(0.05)
            assert h._closed                      # cut also cleaned up
            assert faults.fired("serving.stream.cut") == 1
        finally:
            faults.reset()
            rep.close()


# ---------------------------------------------------------------------------
# routed fleet: chaos matrix + kill-mid-run + heartbeats
# ---------------------------------------------------------------------------
def _fleet(n=3, step_delay_s=0.002, **cfg_over):
    engines = [FakeEngine(step_delay_s=step_delay_s) for _ in range(n)]
    reps = [InProcessReplica(e, replica_id=i)
            for i, e in enumerate(engines)]
    cfg = _cfg(probe_interval_s=0.03, failure_threshold=2,
               breaker_cooldown_s=0.25, dispatch_attempts=4,
               gap_timeout_s=0.5, max_inflight=64, **cfg_over)
    return engines, reps, Router(reps, cfg)


def _run_clients(router, prompts, n_new, spread_s=0.2):
    """Poisson-ish routed load: one client thread per request, arrivals
    spread over `spread_s`. Returns [(tokens, terminal)] in request order."""
    rng = np.random.RandomState(7)
    offsets = np.sort(rng.uniform(0.0, spread_s, len(prompts)))
    results = [None] * len(prompts)

    def client(i):
        time.sleep(float(offsets[i]))
        results[i] = router.generate(_payload(prompts[i], n=n_new))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert time.time() - t0 < 30.0, "routed run hung"
    return results


class TestRoutedChaosMatrix:
    @pytest.mark.parametrize("point", SERVING_POINTS)
    def test_point_recovers_to_fault_free_streams(self, point):
        """The serving chaos matrix: every registered serving.* point
        injected once during a routed Poisson run — zero lost requests,
        and every stream equals the fault-free (deterministic) stream."""
        rng = np.random.RandomState(11)
        prompts = [rng.randint(1, 500, int(n)).astype(np.int32)
                   for n in rng.randint(3, 11, 10)]
        n_new = 6
        engines, reps, router = _fleet()
        try:
            # nth: let the fleet serve a beat first, then fire mid-run.
            # dispatch.drop is hit once PER DISPATCH (~10 hits total);
            # the driver-loop/stream-poll points hit every few ms
            nth = 5 if point == "serving.dispatch.drop" else 40
            faults.arm(point, mode="nth", nth=nth)
            results = _run_clients(router, prompts, n_new)
            assert faults.fired(point) == 1, point
            for i, (toks, term) in enumerate(results):
                assert term is not None, f"request {i} got no terminal"
                assert term.get("done") is True, (point, i, term)
                assert toks == _expected(prompts[i], n_new), (point, i)
        finally:
            faults.reset()
            router.close()
            for rep in reps:
                rep.close()
        # zero per-request residue anywhere after the run
        assert router._inflight == {}
        for eng, rep in zip(engines, reps):
            if rep.dead_cause is None:       # a killed replica keeps its
                eng.allocator.check_consistency()   # corpse state by design
                assert eng.allocator.used_pages == 0
                assert eng.scheduler._by_rid == {}

    def test_kill_one_of_three_mid_run_loses_nothing(self):
        """The acceptance scenario: 1 of 3 replicas killed while streams
        are in flight — every accepted request still completes with the
        exact stream, via failover re-prefill on a peer."""
        rng = np.random.RandomState(23)
        prompts = [rng.randint(1, 500, int(n)).astype(np.int32)
                   for n in rng.randint(3, 11, 9)]
        n_new = 24
        engines, reps, router = _fleet(step_delay_s=0.004)
        killed = False
        try:
            def killer():
                # wait until the victim is actually serving, then kill it
                deadline = time.time() + 5.0
                while time.time() < deadline:
                    if len(engines[1].scheduler.running) > 0:
                        break
                    time.sleep(0.002)
                reps[1].kill()

            kt = threading.Thread(target=killer)
            kt.start()
            results = _run_clients(router, prompts, n_new, spread_s=0.1)
            kt.join(timeout=5.0)
            killed = reps[1].dead_cause is not None
            for i, (toks, term) in enumerate(results):
                assert term is not None and term.get("done") is True, (i, term)
                assert toks == _expected(prompts[i], n_new), i
            assert killed
            # in-flight work on the corpse failed over rather than timing out
            assert router.failovers >= 1
            assert router.stats()["replicas"]["1"]["circuit"] == "open"
        finally:
            faults.reset()
            router.close()
            for rep in reps:
                rep.close()
        assert router._inflight == {}
        for i in (0, 2):
            engines[i].allocator.check_consistency()
            assert engines[i].allocator.used_pages == 0
            assert engines[i].scheduler._by_rid == {}

    def test_heartbeat_corpse_trips_breaker_by_name(self):
        """PR-10 liveness behind the router: a killed replica's heartbeat
        goes stale (no clean-exit tombstone) and the monitor trips its
        breaker from dead_peers() — the SAME machinery training uses."""
        store = TCPStore(is_master=True)
        engines = [FakeEngine(), FakeEngine()]
        reps = [InProcessReplica(e, replica_id=i, store=store,
                                 heartbeat_interval_s=0.02)
                for i, e in enumerate(engines)]
        # failure_threshold high: the probe path must NOT be what trips —
        # only the heartbeat verdict may open the circuit
        r = Router(reps, _cfg(failure_threshold=99), store=store,
                   dead_timeout_s=0.12, start_monitor=False)
        try:
            r.monitor_tick()                  # primes the beat watch
            time.sleep(0.05)
            r.monitor_tick()
            assert r.stats()["replicas"]["1"]["circuit"] == "closed"
            reps[1].kill()
            cause = None
            for _ in range(60):
                time.sleep(0.05)
                r.monitor_tick()
                s = r.stats()["replicas"]["1"]
                if s["circuit"] == "open":
                    cause = s["last_cause"]
                    break
            assert cause is not None and "heartbeat stale" in cause
            assert r.stats()["replicas"]["0"]["circuit"] == "closed"
        finally:
            r.close()
            for rep in reps:
                rep.close()
            store.close()


# ---------------------------------------------------------------------------
# HTTP front door (serve.py chassis, FakeEngine replicas)
# ---------------------------------------------------------------------------
class TestHttpFrontend:
    def _serve(self, router):
        srv = router.serve_http(0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, srv.server_address[1], t

    def _get(self, port, path):
        import http.client
        import json as json_mod

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = json_mod.loads(resp.read().decode())
        conn.close()
        return resp.status, body

    def _post(self, port, payload):
        import http.client
        import json as json_mod

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        body = json_mod.dumps(payload).encode()
        conn.request("POST", "/generate", body,
                     {"Content-Type": "application/json",
                      "Content-Length": str(len(body))})
        resp = conn.getresponse()
        events = [json_mod.loads(l) for l in
                  resp.read().decode().splitlines() if l.strip()]
        headers = dict(resp.getheaders())
        conn.close()
        return resp.status, events, headers

    def test_generate_healthz_stats_roundtrip(self):
        engines, reps, router = _fleet(n=2, step_delay_s=0.0)
        srv = None
        try:
            srv, port, _ = self._serve(router)
            status, body = self._get(port, "/healthz")
            assert status == 200 and body["ok"] is True
            assert sorted(body["healthy"]) == [0, 1]
            p = np.arange(5, 11)
            status, events, _ = self._post(port, _payload(p, n=4))
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            assert toks == _expected(p, 4)
            assert events[-1]["done"] is True
            status, body = self._get(port, "/stats")
            assert status == 200
            assert body["completed"] == 1 and body["accepted"] == 1
            assert body["replicas"]["0"]["circuit"] == "closed"
        finally:
            if srv is not None:
                srv.shutdown()
            router.close()
            for rep in reps:
                rep.close()

    def test_admission_refusal_is_pre_headers_503_with_retry_after(self):
        engines, reps, router = _fleet(n=2, step_delay_s=0.0)
        srv = None
        try:
            for rep in reps:          # kill the whole fleet
                rep.kill()
            for _ in range(2):        # threshold=2 -> both circuits open
                router.monitor_tick()
            srv, port, _ = self._serve(router)
            status, body = self._get(port, "/healthz")
            assert status == 503 and body["ok"] is False
            status, events, headers = self._post(
                port, _payload(np.arange(1, 4)))
            assert status == 503      # refused BEFORE the ndjson stream
            assert "Retry-After" in headers
            assert "error" in events[0]
        finally:
            if srv is not None:
                srv.shutdown()
            router.close()
            for rep in reps:
                rep.close()


# ---------------------------------------------------------------------------
# real engine behind the router: the acceptance criteria
# ---------------------------------------------------------------------------
class TestRoutedRealEngine:
    @pytest.fixture(scope="class")
    def real(self):
        from test_serving import _engine, _model, _prompts

        m, cfg = _model()
        eng = _engine(m)
        rng = np.random.RandomState(0)
        # compile every decode/prefill bucket OUTSIDE the routed run
        eng.generate(_prompts(rng, cfg, (6, 13, 30)), max_new_tokens=4)
        eng.mark_warmup()
        return m, cfg, eng

    def test_routed_parity_zero_retrace_and_clean_release(self, real):
        from test_serving import _prompts, _teacher_greedy

        m, cfg, eng = real
        rep = InProcessReplica(eng, replica_id=0)
        router = Router([rep], _cfg(gap_timeout_s=10.0))
        try:
            rng = np.random.RandomState(9)
            prompts = _prompts(rng, cfg, (5, 11, 8))
            for p in prompts:
                toks, term = router.generate(_payload(p, n=6))
                assert term["done"] and term["failovers"] == 0
                assert toks == _teacher_greedy(m, p, 6)
            # the PR-9 zero-retrace contract must hold BEHIND the router
            assert eng.decode_retraces_after_warmup == 0
            # engine stats feed the probe path end to end
            pr = rep.probe()
            assert pr["decode_retraces_after_warmup"] == 0
            assert pr["slot_fill"] == 0.0
        finally:
            router.close()
            rep.close()
        # no per-request state retained once streams closed
        assert eng.scheduler._by_rid == {}
        assert eng.allocator.used_pages == 0
        assert router._inflight == {}
