"""Scan-over-layers + selective-remat policy suite (ISSUE 2).

Covers: scanned-vs-unrolled forward/grad parity, every remat policy vs
'none', state-dict and optimizer-state round-trips across scan on/off,
mp-sharded scan on the virtual mesh, the per-layer remat exclusion of the
embed/fused-head/CE segment, and the CI guard that lowered HLO size stays
depth-independent under scan (so future edits can't silently re-unroll)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.models.llama import (
    LlamaDecoderLayer, LlamaForCausalLM, llama_tiny_config,
)
from paddle_tpu.parallel import CompiledTrainStep
from paddle_tpu.parallel.scan_layers import (
    REMAT_POLICIES, normalize_remat, remat_wrap,
)


def _model(n_layers=4, scan=False, **over):
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=n_layers, scan_layers=scan,
                            **over)
    return cfg, LlamaForCausalLM(cfg)


def _data(cfg, batch=2, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    return ids, labels


def _train_losses(model, n_steps, ids, labels, scan=False, remat="none",
                  optimizer=None, mesh=None):
    opt = optimizer or paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters())
    step = CompiledTrainStep(model, lambda out, lab: out, optimizer=opt,
                             scan_layers=scan, remat=remat, mesh=mesh)
    return [float(step(ids, labels, labels)) for _ in range(n_steps)], step


class TestNormalize:
    def test_bool_and_string_mapping(self):
        assert normalize_remat(True) == "full"
        assert normalize_remat(False) == "none"
        assert normalize_remat(None) == "none"
        for p in REMAT_POLICIES:
            assert normalize_remat(p) == p

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="unknown remat policy"):
            normalize_remat("everything")

    def test_remat_wrap_none_is_identity(self):
        f = lambda x: x * 2  # noqa: E731
        assert remat_wrap(f, "none") is f


class TestEagerParity:
    def test_scanned_matches_unrolled_loss_and_grads(self):
        """Scanned forward/backward == unrolled, through the eager tape."""
        cfg, m_u = _model(4, scan=False)
        _, m_s = _model(4, scan=True)
        m_s.set_state_dict(m_u.state_dict())
        ids, labels = _data(cfg)
        lu = m_u(ids, labels)
        ls = m_s(ids, labels)
        np.testing.assert_allclose(float(lu), float(ls), rtol=1e-6)
        lu.backward()
        ls.backward()
        gu = dict(m_u.named_parameters())
        gs = dict(m_s.named_parameters())
        assert set(gu) == set(gs)
        for n in gu:
            assert gs[n].grad is not None, f"no grad for {n} under scan"
            np.testing.assert_allclose(
                np.asarray(gu[n].grad._value), np.asarray(gs[n].grad._value),
                rtol=1e-5, atol=1e-6, err_msg=n)


class TestCompiledParity:
    def _reference(self):
        cfg, m = _model(4)
        ids, labels = _data(cfg)
        losses, _ = _train_losses(m, 3, ids, labels)
        return cfg, ids, labels, losses

    @pytest.mark.parametrize("scan", [False, True])
    @pytest.mark.parametrize("remat", ["full", "save_dots", "save_nothing",
                                       "offload_residuals"])
    def test_policies_match_none(self, scan, remat):
        """Remat policies change memory, never math: per-step losses must
        match the no-remat run exactly (same program modulo recompute)."""
        ref = getattr(TestCompiledParity, "_ref_cache", None)
        if ref is None:
            ref = self._reference()
            TestCompiledParity._ref_cache = ref
        cfg, ids, labels, ref_losses = ref
        _, m = _model(4, scan=scan)
        losses, step = _train_losses(m, 3, ids, labels, scan=scan,
                                     remat=remat)
        assert step.scan_layers == scan
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-6, atol=1e-6)

    def test_legacy_bool_remat_non_cooperating_model(self):
        """remat=True on a model WITHOUT the cooperation protocol falls back
        to the legacy whole-loss checkpoint and still matches."""
        ref = self._reference()
        cfg, ids, labels, ref_losses = ref
        _, m = _model(4)

        class Wrap:  # hides layer_remat_capable / scan_group
            def parameters(self):
                return m.parameters()

            def __call__(self, i, l):
                return m(i, l)

        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = CompiledTrainStep(Wrap(), lambda o, l: o, optimizer=opt,
                                 remat=True)
        assert step.remat_policy == "full" and not step._layer_capable
        losses = [float(step(ids, labels, labels)) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-6, atol=1e-6)


class TestPackingGate:
    def test_scan_group_without_context_cooperation_not_packed(self):
        """A model exposing scan_group() but NOT reading the layer-execution
        context must not be packed: its forward would trace stale concrete
        params as constants and train frozen weights."""
        _, m = _model(4)

        class HalfProtocol:  # scan_group but no layer_remat_capable
            def parameters(self):
                return m.parameters()

            def scan_group(self):
                return m.scan_group()

            def __call__(self, i, l):
                return m(i, l)

        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = CompiledTrainStep(HalfProtocol(), lambda o, l: o,
                                 optimizer=opt, scan_layers=True)
        assert not step.scan_layers

    def test_trust_ratio_optimizers_not_packed(self):
        """Lamb/Lars compute a per-PARAMETER trust-ratio norm; over a stacked
        [L, ...] entry that would couple all layers into one ratio, so
        packing must auto-disable (scan still runs in-program via config)."""
        cfg, m = _model(4, scan=True)
        opt = paddle.optimizer.Lamb(learning_rate=1e-3,
                                    parameters=m.parameters())
        step = CompiledTrainStep(m, lambda o, l: o, optimizer=opt,
                                 scan_layers=True)
        assert not step.scan_layers
        ids, labels = _data(cfg)
        losses = [float(step(ids, labels, labels)) for _ in range(2)]
        assert losses[1] < losses[0]


class TestHeadOutsideRematRegion:
    def _gather_count(self, remat, cooperate):
        paddle.seed(0)
        cfg = llama_tiny_config(num_hidden_layers=2)
        m = LlamaForCausalLM(cfg)
        target = m
        if not cooperate:
            class W:
                def parameters(self):
                    return m.parameters()

                def __call__(self, i, l):
                    return m(i, l)

            target = W()
        opt = paddle.optimizer.SGD(learning_rate=1e-3,
                                   parameters=m.parameters())
        step = CompiledTrainStep(target, lambda o, l: o, optimizer=opt,
                                 remat=remat)
        rng = np.random.RandomState(0)
        iv = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        low = jax.jit(step._step_fn).lower(
            step._param_vals, step._opt_states, (iv, iv, iv),
            jax.random.key(0), jnp.float32(1e-3), jnp.int32(1))
        return low.as_text().count("stablehlo.gather")

    def test_fused_head_and_embed_computed_once_under_full_remat(self):
        """Satellite fix: 'full' remat on a cooperating model wraps ONLY the
        decoder layers, so the embedding lookup and the fused head/CE label
        gather appear exactly once in the lowered program — unlike the
        legacy whole-loss region, which recomputes both in backward."""
        base = self._gather_count("none", cooperate=True)
        coop = self._gather_count("full", cooperate=True)
        legacy = self._gather_count("full", cooperate=False)
        assert coop == base, (
            f"per-layer remat recomputes embed/head gathers: {coop} != {base}")
        assert legacy > base, (
            "legacy whole-loss remat unexpectedly stopped recomputing — "
            "update this test's discriminator")


class TestHLODepthIndependence:
    """CI guard (ISSUE 2 satellite): scanned HLO must not grow with depth,
    and a scan/while loop must actually be present — so future edits can't
    silently re-unroll the stack."""

    def _lowered_text(self, n_layers, scan):
        _, m = _model(n_layers)
        opt = paddle.optimizer.SGD(learning_rate=1e-3,
                                   parameters=m.parameters())
        step = CompiledTrainStep(m, lambda o, l: o, optimizer=opt,
                                 scan_layers=scan)
        assert step.scan_layers == scan
        rng = np.random.RandomState(0)
        iv = jnp.asarray(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
        low = jax.jit(step._step_fn).lower(
            step._param_vals, step._opt_states, (iv, iv, iv),
            jax.random.key(0), jnp.float32(1e-3), jnp.int32(1))
        return low.as_text()

    def test_hlo_size_depth_independent_under_scan(self):
        t2 = self._lowered_text(2, scan=True)
        t8 = self._lowered_text(8, scan=True)
        ratio = len(t8) / len(t2)
        assert ratio <= 1.15, (
            f"scanned 8-layer HLO is {ratio:.2f}x the 2-layer HLO — "
            "the stack re-unrolled")
        assert "stablehlo.while" in t8, "no scan/while loop in scanned HLO"

    def test_unrolled_hlo_grows_with_depth(self):
        """The guard above is only meaningful if depth actually inflates the
        unrolled program on this toolchain."""
        t2 = self._lowered_text(2, scan=False)
        t8 = self._lowered_text(8, scan=False)
        assert len(t8) / len(t2) > 1.5


class TestStateDictRoundTrip:
    def test_scan_to_unrolled_checkpoint_resume(self):
        """Train scanned 2 steps -> checkpoint (params + optimizer moments)
        -> resume UNROLLED; the continued trajectory must match a pure
        unrolled 4-step run. Proves state-dict layout and per-layer optimizer
        state are identical across scan on/off."""
        cfg, m_ref = _model(4)
        ids, labels = _data(cfg)
        ref_losses, _ = _train_losses(m_ref, 4, ids, labels)

        _, m_a = _model(4, scan=True)
        opt_a = paddle.optimizer.AdamW(learning_rate=1e-3,
                                       parameters=m_a.parameters())
        first, step_a = _train_losses(m_a, 2, ids, labels, scan=True,
                                      optimizer=opt_a)
        step_a.sync_params_to_model()
        step_a.sync_states_to_optimizer()
        sd = {k: np.asarray(v._value) for k, v in m_a.state_dict().items()}
        opt_sd = opt_a.state_dict()

        _, m_b = _model(4, scan=False)
        missing, unexpected = m_b.set_state_dict(sd)
        assert not missing and not unexpected
        opt_b = paddle.optimizer.AdamW(learning_rate=1e-3,
                                       parameters=m_b.parameters())
        opt_b.set_state_dict(opt_sd)
        rest, _ = _train_losses(m_b, 2, ids, labels, scan=False,
                                optimizer=opt_b)
        np.testing.assert_allclose(first + rest, ref_losses,
                                   rtol=2e-5, atol=2e-5)


class TestMeshScan:
    def test_mp_sharded_scan_matches_dense(self):
        """Scanned training on an mp=2 (x dp=2) virtual mesh: the stacked
        [L, ...] params carry PartitionSpec(None, *mp_spec) and losses match
        the dense unsharded run."""
        cfg, m_ref = _model(4)
        ids, labels = _data(cfg, batch=4)
        set_mesh(None)
        ref_losses, _ = _train_losses(m_ref, 3, ids, labels)
        try:
            mesh = build_mesh({"dp": 2, "mp": 2})
            _, m = _model(4, scan=True)
            losses, step = _train_losses(m, 3, ids, labels, scan=True,
                                         remat="save_dots", mesh=mesh)
            assert step.scan_layers
            # at least one stacked param must actually be mp-sharded beyond
            # the leading (layer) dim
            specs = step._param_specs[len(step._outer_params):]
            assert any("mp" in [a for e in s for a in
                                ((e,) if not isinstance(e, tuple) else e)
                                if e] for s in specs), specs
        finally:
            set_mesh(None)
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-4)


class TestRopeHoist:
    def test_single_shared_rope_buffer_pair(self):
        """Satellite: ONE rope table pair on LlamaModel instead of one per
        attention layer; state_dict layout unchanged (tables are
        non-persistable)."""
        cfg, m = _model(4)
        bufs = dict(m.llama.named_buffers())
        rope_keys = [k for k in bufs if "rope" in k]
        assert sorted(rope_keys) == ["rope_cos", "rope_sin"], rope_keys
        for layer in m.llama.layers:
            assert not list(layer.named_buffers())
        assert not any("rope" in k for k in m.state_dict())

    def test_standalone_decoder_layer_falls_back_to_shared_cache(self):
        """Pipeline LayerDesc stages call blocks without the model-level
        rope; the process-wide cached tables must kick in and match the
        in-model result."""
        cfg, m = _model(2)
        ids, _ = _data(cfg)
        x = m.llama.embed_tokens(ids)
        via_model = m.llama.layers[0](
            x, None, rope=(m.llama.rope_cos._value, m.llama.rope_sin._value))
        standalone = m.llama.layers[0](x)
        np.testing.assert_allclose(np.asarray(via_model._value),
                                   np.asarray(standalone._value),
                                   rtol=1e-6, atol=1e-6)


class TestZeroBubblePolicy:
    def test_zbh1_rejects_recompute_policies(self):
        from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

        with pytest.raises(ValueError, match="zero-recompute"):
            ZBH1PipelinedStep(None, [], None, None, remat="full")

    def test_zbh1_accepts_none(self):
        from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

        # 'none' passes policy validation and proceeds to the mesh check
        with pytest.raises(ValueError, match="mesh"):
            ZBH1PipelinedStep(None, [], None, None, remat=False)
