"""Sequence packing end-to-end: first-fit packer round-trips, segment-aware
flash kernel (fwd + dq/dk/dv) parity vs the masked XLA reference, packed-vs-
padded loss equivalence through the model, and extra-batch-leaf delivery in
all three compiled train-step runtimes (CompiledTrainStep dict batches, 1F1B
per-tick segment context, ZB-H1 stashed-residual context)."""
import math
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.flags import set_flags
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io.packing import (IGNORE_INDEX, SequencePacker, pack_examples,
                                   packing_stats, pad_examples, unpack_batch)
from paddle_tpu.ops.pallas.flash_attention import (flash_attention_bshd,
                                                   segment_block_visit_counts)


def _docs(rng, n, vocab, lo=3, hi=40):
    return [rng.randint(1, vocab, rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# packer
# ---------------------------------------------------------------------------

class TestPacker:
    def test_round_trip_every_token_exactly_once(self):
        rng = np.random.RandomState(0)
        docs = _docs(rng, 53, 1000, 2, 64)  # <= seq_len: no chunk splits
        got = []
        for b in pack_examples(iter(docs), seq_len=64, batch_size=4):
            assert b["input_ids"].shape == (4, 64)
            got.extend(tuple(d) for d in unpack_batch(b))
        assert sorted(got) == sorted(tuple(d) for d in docs)

    def test_labels_positions_segments(self):
        rng = np.random.RandomState(1)
        docs = _docs(rng, 24, 500)
        for b in pack_examples(iter(docs), seq_len=48, batch_size=2):
            ids, lab = b["input_ids"], b["labels"]
            seg, pos = b["segment_ids"], b["position_ids"]
            for r in range(ids.shape[0]):
                # segment ids non-decreasing (tight kernel block ranges)
                assert (np.diff(seg[r]) >= 0).all()
                starts = [0] + (1 + np.flatnonzero(np.diff(seg[r]))).tolist()
                # positions restart at 0 at every segment boundary
                assert all(pos[r, s] == 0 for s in starts)
                # the token before each boundary predicts nothing
                assert all(lab[r, s - 1] == IGNORE_INDEX for s in starts[1:])
                # non-ignored labels are the next token of the same segment
                for i in np.flatnonzero(lab[r] != IGNORE_INDEX):
                    assert lab[r, i] == ids[r, i + 1]
                    assert seg[r, i] == seg[r, i + 1]

    def test_long_document_chunked(self):
        doc = np.arange(1, 300, dtype=np.int32)
        batches = list(pack_examples(iter([doc]), seq_len=64, batch_size=2))
        cat = np.concatenate(
            [t for b in batches for t in unpack_batch(b)])
        np.testing.assert_array_equal(cat, doc)

    def test_first_fit_backfills(self):
        # 40 + 30 leave gaps a 20 and a 24 backfill: ONE batch of 2 rows
        docs = [np.ones(40, np.int32), np.ones(30, np.int32),
                np.ones(20, np.int32), np.ones(24, np.int32)]
        batches = list(pack_examples(iter(docs), seq_len=60, batch_size=2))
        assert len(batches) == 1
        assert len(unpack_batch(batches[0])) == 4

    def test_stats_padding_fraction(self):
        st = packing_stats([30, 10, 50, 20], seq_len=50, batch_size=2)
        assert st["padded_rows"] == 4
        assert st["padding_frac_padded"] == pytest.approx(1 - 110 / 200)
        assert st["packed_rows"] < st["padded_rows"]

    def test_flush_emits_partial(self):
        p = SequencePacker(seq_len=16, batch_size=2)
        assert p.feed(np.ones(10, np.int32)) == []
        tail = p.flush()
        assert tail is not None and tail["input_ids"].shape == (2, 16)
        assert p.flush() is None


# ---------------------------------------------------------------------------
# segment-aware kernel parity (interpret mode: the tier-1 TPU-code path)
# ---------------------------------------------------------------------------

def _ref_gqa_seg(q, k, v, causal, seg):
    """Dense masked reference: GQA repeat + causal + block-diagonal segs."""
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    qh, kh, vh = [jnp.swapaxes(x.astype(jnp.float32), 1, 2)
                  for x in (q, k, v)]
    kh = jnp.repeat(kh, hq // hkv, axis=1)
    vh = jnp.repeat(vh, hq // hkv, axis=1)
    sc = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(d)
    mask = jnp.ones((s, s), bool)
    if causal:
        mask = jnp.tril(mask)
    mask = mask[None, None] & (seg[:, None, :, None] == seg[:, None, None, :])
    sc = jnp.where(mask, sc, -1e30)
    p = jax.nn.softmax(sc, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _packed_seg(rng, b, s):
    seg = np.zeros((b, s), np.int32)
    for r in range(b):
        cuts = np.sort(rng.choice(np.arange(8, s - 8), 3, replace=False))
        bounds = [0] + cuts.tolist() + [s]
        for i, (a, e) in enumerate(zip(bounds[:-1], bounds[1:])):
            seg[r, a:e] = i + 1
    return jnp.asarray(seg)


class TestSegmentKernel:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("heads", [(4, 2), (4, 1), (2, 2)])
    def test_fwd_bwd_parity_gqa_fp32(self, flash_interpret, causal, heads):
        hq, hkv = heads
        rng = np.random.RandomState(2)
        b, s, d = 2, 128, 32
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        seg = _packed_seg(rng, b, s)
        out = flash_attention_bshd(q, k, v, causal=causal, segment_ids=seg)
        ref = _ref_gqa_seg(q, k, v, causal, seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=1e-5)
        g1 = jax.grad(lambda *a: flash_attention_bshd(
            *a, causal=causal, segment_ids=seg).sum(), (0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: _ref_gqa_seg(
            *a, causal, seg).sum(), (0, 1, 2))(q, k, v)
        for a, r in zip(g1, g2):  # dq, dk, dv parity
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-3, atol=2e-4)

    def test_fwd_parity_bf16(self, flash_interpret):
        rng = np.random.RandomState(3)
        b, s, d = 1, 128, 32
        q = jnp.asarray(rng.randn(b, s, 4, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, s, 2, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, s, 2, d), jnp.bfloat16)
        seg = _packed_seg(rng, b, s)
        out = flash_attention_bshd(q, k, v, causal=True, segment_ids=seg)
        ref = _ref_gqa_seg(q, k, v, True, seg)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=1e-3)  # <= 1e-3 abs, the bf16 acceptance bar

    def test_block_skip_flag_does_not_change_math(self, flash_interpret):
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(1, 128, 2, 16), jnp.float32)
        seg = _packed_seg(rng, 1, 128)
        out_skip = flash_attention_bshd(q, q, q, causal=True, segment_ids=seg)
        set_flags({"flash_segment_block_skip": False})
        try:
            out_mask = flash_attention_bshd(q, q, q, causal=True,
                                            segment_ids=seg)
        finally:
            set_flags({"flash_segment_block_skip": True})
        np.testing.assert_allclose(np.asarray(out_skip), np.asarray(out_mask),
                                   rtol=1e-5, atol=1e-6)

    def test_visit_counter_skips_blocks_under_packing(self, flash_interpret):
        s, bq = 128, 16
        seg_packed = np.repeat(np.arange(1, 5), s // 4)[None]  # 4 docs
        seg_one = np.ones((1, s), np.int32)                    # 1 doc
        c_packed = int(np.sum(np.asarray(segment_block_visit_counts(
            seg_packed, bq, bq, causal=True))))
        c_dense = int(np.sum(np.asarray(segment_block_visit_counts(
            seg_one, bq, bq, causal=True))))
        nq = s // bq
        assert c_dense == nq * (nq + 1) // 2  # causal dense baseline
        # 4 equal docs: ~sum len_i^2 / S^2 = 1/4 of dense
        per_doc = (nq // 4) * (nq // 4 + 1) // 2
        assert c_packed == 4 * per_doc
        assert c_packed < c_dense

    def test_sdpa_routes_segments_through_kernel(self, flash_interpret):
        rng = np.random.RandomState(5)
        q = paddle.to_tensor(rng.randn(2, 64, 4, 16).astype(np.float32))
        k = paddle.to_tensor(rng.randn(2, 64, 2, 16).astype(np.float32))
        v = paddle.to_tensor(rng.randn(2, 64, 2, 16).astype(np.float32))
        seg = _packed_seg(rng, 2, 64)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             segment_ids=Tensor(seg))
        ref = _ref_gqa_seg(q._value, k._value, v._value, True, seg)
        np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                                   rtol=2e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# SDPA fallback satellites
# ---------------------------------------------------------------------------

class TestSdpaMaskComposition:
    def _qkv(self, s=16):
        rng = np.random.RandomState(6)
        return [paddle.to_tensor(rng.randn(1, s, 2, 8).astype(np.float32))
                for _ in range(3)]

    def test_bool_mask_and_causal_both_apply(self):
        q, k, v = self._qkv()
        m = np.ones((1, 1, 16, 16), bool)
        m[..., 5] = False  # block key 5 for everyone
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(m), is_causal=True)
        # reference: combined bool mask
        comb = np.tril(np.ones((16, 16), bool)) & m[0, 0]
        qh, kh, vh = [np.swapaxes(t._value, 1, 2) for t in (q, k, v)]
        sc = np.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(8)
        sc = np.where(comb, sc, -1e30)
        p = jax.nn.softmax(jnp.asarray(sc), axis=-1)
        ref = np.swapaxes(np.einsum("bhqk,bhkd->bhqd", np.asarray(p), vh), 1, 2)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=2e-4, atol=1e-5)

    def test_additive_mask_and_causal_compose_finite(self):
        q, k, v = self._qkv()
        # the common paddle idiom: finfo.min additive mask; combined with
        # causal this used to overflow toward -inf/NaN territory
        mf = np.zeros((1, 1, 16, 16), np.float32)
        mf[..., :8] = np.finfo(np.float32).min
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(mf), is_causal=True)
        assert np.isfinite(np.asarray(out._value)).all()
        # causal must still win where the additive mask allows: row 0 can
        # only see key 0 causally, which the mask penalizes — but keys > 0
        # (causally masked) must get NO weight, so out[0] == v[key 0]
        np.testing.assert_allclose(np.asarray(out._value)[0, 0],
                                   np.asarray(v._value)[0, 0],
                                   rtol=1e-5, atol=1e-6)

    def test_segment_mask_composes_with_explicit_mask(self):
        q, k, v = self._qkv()
        seg = jnp.asarray(np.repeat([1, 2], 8)[None], jnp.int32)
        m = np.ones((1, 1, 16, 16), bool)
        m[..., 0] = False
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(m), is_causal=True,
            segment_ids=Tensor(seg))
        comb = (np.tril(np.ones((16, 16), bool)) & m[0, 0]
                & (np.asarray(seg)[0][:, None] == np.asarray(seg)[0][None, :]))
        qh, kh, vh = [np.swapaxes(t._value, 1, 2) for t in (q, k, v)]
        sc = np.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(8)
        sc = np.where(comb, sc, -1e30)
        p = np.asarray(jax.nn.softmax(jnp.asarray(sc), axis=-1))
        ref = np.swapaxes(np.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=2e-4, atol=1e-5)

    def test_bad_block_flags_fall_back_with_one_warning(self, flash_interpret):
        import paddle_tpu.nn.functional as Fmod

        q, k, v = self._qkv(s=48)  # 48 not divisible by the 36 override
        set_flags({"flash_block_q": 36, "flash_block_k": 36})
        Fmod._warned_pallas_blocks.clear()
        try:
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out1 = F.scaled_dot_product_attention(q, k, v, is_causal=True)
                out2 = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            named = [x for x in w
                     if "FLAGS_flash_block" in str(x.message)]
            assert len(named) == 1  # one-time warning naming the flags
        finally:
            set_flags({"flash_block_q": 0, "flash_block_k": 0})
            Fmod._warned_pallas_blocks.clear()
        # and the XLA fallback produced the right math
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(out1._value),
                                   np.asarray(ref._value), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(out1._value),
                                   np.asarray(out2._value), rtol=0, atol=0)


# ---------------------------------------------------------------------------
# model-level equivalence
# ---------------------------------------------------------------------------

def _tiny_cfg(**kw):
    from paddle_tpu.models.llama import llama_tiny_config

    base = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=64)
    base.update(kw)
    return llama_tiny_config(**base)


class TestModelEquivalence:
    def test_noop_packing_matches_plain_causal(self):
        """Packing a no-op (one doc per row at offset 0): the segment-aware
        loss equals the plain causal loss exactly — per-token and mean."""
        from paddle_tpu.models.llama import LlamaForCausalLM

        rng = np.random.RandomState(7)
        docs = _docs(rng, 4, 128, 20, 30)
        (b,) = list(pad_examples(iter(docs), 40, 4))
        cfg = _tiny_cfg(max_position_embeddings=40)
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(b["input_ids"])
        lab = paddle.to_tensor(b["labels"])
        plain = m(ids, lab)
        packed = m(ids, lab, segment_ids=paddle.to_tensor(b["segment_ids"]),
                   position_ids=paddle.to_tensor(b["position_ids"]))
        assert float(plain._value) == pytest.approx(float(packed._value),
                                                    abs=1e-6)

    def test_packed_per_token_logprobs_match_padded(self):
        """The real guarantee: every document's per-token log-probs are
        IDENTICAL whether the doc sits alone in a padded row or fused with
        neighbors in a packed row (segment mask isolates attention, position
        ids restart RoPE)."""
        from paddle_tpu.models.llama import LlamaForCausalLM

        rng = np.random.RandomState(8)
        docs = _docs(rng, 6, 128, 8, 20)
        S = 64
        packed = list(pack_examples(iter(docs), S, 2))
        padded = list(pad_examples(iter(docs), S, 2))
        cfg = _tiny_cfg()
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()

        def per_doc_nll(batches):
            out = {}
            for b in batches:
                logits = m(paddle.to_tensor(b["input_ids"]),
                           segment_ids=paddle.to_tensor(b["segment_ids"]),
                           position_ids=paddle.to_tensor(b["position_ids"]))
                lp = jax.nn.log_softmax(
                    logits._value.astype(jnp.float32), axis=-1)
                ids, lab = b["input_ids"], b["labels"]
                seg = b["segment_ids"]
                for r in range(ids.shape[0]):
                    bounds = [0] + (1 + np.flatnonzero(
                        np.diff(seg[r]))).tolist() + [S]
                    for a, e in zip(bounds[:-1], bounds[1:]):
                        if (lab[r, a:e] == IGNORE_INDEX).all():
                            continue
                        doc = tuple(ids[r, a:e])
                        nll = [float(lp[r, i, lab[r, i]])
                               for i in range(a, e)
                               if lab[r, i] != IGNORE_INDEX]
                        out[doc] = nll
            return out

        np_packed = per_doc_nll(packed)
        np_padded = per_doc_nll(padded)
        assert set(np_packed) == set(np_padded) and len(np_packed) == 6
        for doc, nll in np_packed.items():
            np.testing.assert_allclose(nll, np_padded[doc], rtol=2e-4,
                                       atol=2e-4)


# ---------------------------------------------------------------------------
# train-step runtimes
# ---------------------------------------------------------------------------

class TestRuntimes:
    def test_compiled_step_dict_batches_no_retrace(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.parallel import CompiledTrainStep

        rng = np.random.RandomState(9)
        docs = _docs(rng, 30, 128)
        batches = list(pack_examples(iter(docs), 32, 4))
        assert len(batches) >= 3
        cfg = _tiny_cfg(max_position_embeddings=32)
        try:
            build_mesh({"dp": 2})
            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            m.train()
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            step = CompiledTrainStep(m, lambda out, lab: out, optimizer=opt)
            losses = [float(step(b)._value) for b in batches]
            assert all(np.isfinite(losses))
            # one cached sharding signature -> no per-step respec/retrace
            assert len(step._spec_cache._cache) == 1
            with pytest.raises(ValueError, match="labels"):
                step({"input_ids": batches[0]["input_ids"]})
        finally:
            set_mesh(None)

    def test_batch_spec_cache_shards_segment_leaves_like_input_ids(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.io.device_feed import BatchSpecCache

        try:
            mesh = build_mesh({"dp": 2})
            cache = BatchSpecCache(mesh, None)
            b = next(pack_examples(
                iter(_docs(np.random.RandomState(10), 8, 64)), 32, 4))
            keys = sorted(b)
            shardings = cache.shardings([jnp.asarray(b[k]) for k in keys])
            specs = {k: s.spec for k, s in zip(keys, shardings)}
            assert specs["segment_ids"] == specs["input_ids"]
            assert specs["position_ids"] == specs["input_ids"]
        finally:
            set_mesh(None)

    def test_feeder_runs_packer_off_critical_path(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.io import prefetch_to_device
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.parallel import CompiledTrainStep

        rng = np.random.RandomState(11)
        docs = _docs(rng, 20, 128)
        direct = list(pack_examples(iter(docs), 32, 2))
        cfg = _tiny_cfg(max_position_embeddings=32)

        def make_step():
            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            m.train()
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            return CompiledTrainStep(m, lambda out, lab: out, optimizer=opt)

        try:
            mesh = build_mesh({"dp": 1})
            step = make_step()
            ref = [float(step(b)._value) for b in direct]
            step2 = make_step()
            with prefetch_to_device(pack_examples(iter(docs), 32, 2),
                                    mesh, step2.batch_spec) as feeder:
                got = [float(step2(b)._value) for b in feeder]
            assert got == ref  # packer+feeder path is bit-identical
            assert step2.h2d_transfers == 0  # batches arrived pre-placed
        finally:
            set_mesh(None)

    def _pipeline_fixture(self, seed=0):
        from paddle_tpu.models.llama import (LlamaDecoderLayer,
                                             _EmbeddingStage, _HeadStage)

        cfg = _tiny_cfg(max_position_embeddings=32, num_key_value_heads=4)
        paddle.seed(seed)
        embed = _EmbeddingStage(cfg)
        blocks = [LlamaDecoderLayer(cfg) for _ in range(2)]
        head = _HeadStage(cfg)

        def loss_fn(logits, labels):
            return F.cross_entropy(
                logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

        return cfg, embed, blocks, head, loss_fn

    def _eager_mb_mean_loss(self, embed, blocks, head, loss_fn, b, M):
        from paddle_tpu.parallel.segments import segment_execution

        rows = b["input_ids"].shape[0]
        mb = rows // M
        tot = 0.0
        for m in range(M):
            sl = slice(m * mb, (m + 1) * mb)
            x = embed(Tensor(b["input_ids"][sl]))
            with segment_execution(b["segment_ids"][sl],
                                   b["position_ids"][sl]):
                for blk in blocks:
                    x = blk(x)
            tot += float(loss_fn(head(x), Tensor(b["labels"][sl]))._value)
        return tot / M

    @pytest.mark.slow
    def test_1f1b_packed_matches_eager(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.parallel.pipeline import PipelinedTrainStep

        rng = np.random.RandomState(12)
        b = next(pack_examples(iter(_docs(rng, 10, 128, 5, 15)), 32, 4))
        try:
            build_mesh({"pp": 2})
            cfg, embed, blocks, head, loss_fn = self._pipeline_fixture()
            ref = self._eager_mb_mean_loss(embed, blocks, head, loss_fn, b, 2)
            params = (embed.parameters()
                      + [p for bl in blocks for p in bl.parameters()]
                      + head.parameters())
            opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=params)
            step = PipelinedTrainStep(embed, blocks, head, loss_fn,
                                      optimizer=opt, num_micro=2, remat=False)
            loss = float(step(b["input_ids"], b["labels"],
                              segment_ids=b["segment_ids"],
                              position_ids=b["position_ids"])._value)
            assert loss == pytest.approx(ref, abs=2e-4)
        finally:
            set_mesh(None)

    def test_1f1b_vpp_rejects_extras(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.parallel.pipeline import PipelinedTrainStep

        rng = np.random.RandomState(13)
        b = next(pack_examples(iter(_docs(rng, 10, 128, 5, 15)), 32, 4))
        try:
            build_mesh({"pp": 2})
            cfg, embed, blocks, head, loss_fn = self._pipeline_fixture()
            blocks = blocks + blocks  # 4 blocks for V=2
            step = PipelinedTrainStep(embed, blocks, head, loss_fn,
                                      num_micro=2, remat=False, virtual_pp=2)
            with pytest.raises(ValueError, match="virtual-pp"):
                step(b["input_ids"], b["labels"],
                     segment_ids=b["segment_ids"])
        finally:
            set_mesh(None)

    @pytest.mark.slow
    def test_zbh1_packed_matches_eager(self):
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

        rng = np.random.RandomState(14)
        b = next(pack_examples(iter(_docs(rng, 10, 128, 5, 15)), 32, 4))
        try:
            build_mesh({"pp": 2})
            cfg, embed, blocks, head, loss_fn = self._pipeline_fixture()
            ref = self._eager_mb_mean_loss(embed, blocks, head, loss_fn, b, 2)
            step = ZBH1PipelinedStep(embed, blocks, head, loss_fn,
                                     num_micro=2)
            loss, _ = step.run(b["input_ids"], b["labels"],
                               segment_ids=b["segment_ids"],
                               position_ids=b["position_ids"])
            assert float(loss) == pytest.approx(ref, abs=2e-4)
        finally:
            set_mesh(None)
