"""Serving engine, tier-1 core: KV page allocator invariants (exhaustion
-> eviction order, chain free, aliasing, mid-decode cancel), sampling,
decode parity vs the full-sequence forward THROUGH the interpret-mode
Pallas paged kernel (incl. GQA/bf16 <= 1e-3), the zero-retrace contract,
and the serving-package pickle grep guard. System-level scheduling + HTTP
coverage lives in test_serving_system.py (slow tier — each extra engine
costs a fresh XLA compile, and tier-1 runs near its wall-clock budget)."""
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import (ContinuousBatchingScheduler, PageAllocator,
                                Request, RequestState, ServingConfig,
                                ServingEngine, kv_page_bytes,
                                pages_for_budget, sample_tokens)


def _model(**over):
    paddle.seed(0)
    cfg = llama_tiny_config(**over)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, **over):
    kw = dict(page_size=4, num_pages=64, decode_batch=4, prefill_chunk=8,
              max_seq_len=64)
    kw.update(over)
    return ServingEngine(m, ServingConfig(**kw))


def _prompts(rng, cfg, lens):
    return [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
            for n in lens]


# ONE shared model + engine for the engine-level tests: every ServingEngine
# owns its own jit closures, so each extra engine costs a fresh decode +
# prefill compile (~4 s on the CI box). Tests must leave the engine idle
# (generate() and cancel() free all pages).
@pytest.fixture(scope="module")
def shared():
    m, cfg = _model()
    return m, cfg, _engine(m)


_teacher_fwd_cache = {}


def _teacher_greedy(m, prompt, n, pad=64):
    """Greedy continuation via the FULL-sequence forward, jitted ONCE on a
    padded frame (causal attention: tail padding can't affect the logits
    at the last real position) — an eager per-token loop would dominate
    the suite's wall clock."""
    from paddle_tpu.parallel.train_step import functional_call

    if id(m) not in _teacher_fwd_cache:
        params = [p._value for p in m.parameters()]

        def fwd(params, ids):
            out = functional_call(m, params, (ids,), training=False)
            return out._value

        _teacher_fwd_cache[id(m)] = (jax.jit(fwd), params)
    fn, params = _teacher_fwd_cache[id(m)]
    seq = [int(t) for t in np.asarray(prompt)]
    for _ in range(n):
        ids = np.zeros((1, pad), np.int64)
        ids[0, :len(seq)] = seq
        lg = np.asarray(fn(params, jnp.asarray(ids)), np.float32)
        seq.append(int(np.argmax(lg[0, len(seq) - 1])))
    return seq[len(prompt):]


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

class TestPageAllocator:
    def test_all_or_nothing_and_chain_free(self):
        a = PageAllocator(num_pages=6, page_size=4)      # 5 usable
        assert a.ensure("r0", 9)                          # 3 pages
        assert a.free_pages == 2
        assert not a.ensure("r1", 12)                     # needs 3 > 2 free
        assert a.free_pages == 2 and a.chain("r1") == []  # nothing leaked
        assert a.ensure("r1", 8)                          # 2 pages fits
        a.check_consistency()
        assert a.free_request("r0") == 3
        assert a.free_pages == 3
        a.check_consistency()

    def test_no_aliasing_across_concurrent_requests(self):
        a = PageAllocator(num_pages=32, page_size=2)
        rng = np.random.RandomState(0)
        live = {}
        for step in range(200):
            rid = rng.randint(8)
            if rid in live and rng.rand() < 0.3:
                a.free_request(rid)
                del live[rid]
            else:
                tokens = live.get(rid, 0) + rng.randint(1, 5)
                if a.ensure(rid, tokens):
                    live[rid] = tokens
            a.check_consistency()
        rows = [a.page_table_row(r, 16) for r in live]
        used = [p for row in rows for p in row if p != 0]
        assert len(used) == len(set(used))               # no shared pages

    def test_null_page_never_allocated(self):
        a = PageAllocator(num_pages=4, page_size=1)
        a.ensure("r", 3)                                  # the whole pool
        assert 0 not in a.chain("r")
        row = a.page_table_row("r", 8)
        assert row[3:].tolist() == [0] * 5                # null-padded

    def test_budget_accounting(self):
        pb = kv_page_bytes(num_layers=2, num_kv_heads=2, page_size=16,
                           head_dim=64, dtype_bytes=2)
        assert pb == 2 * 2 * 2 * 16 * 64 * 2      # k+v * L * H * ps * D * b
        assert pages_for_budget(10 * pb, pb) == 10
        # PR-16 hardening: budgets that cannot back a working pool fail
        # LOUDLY at sizing time, not later inside the engine
        with pytest.raises(ValueError, match="positive"):
            pages_for_budget(0, pb)
        with pytest.raises(ValueError, match="positive"):
            pages_for_budget(-1, pb)
        with pytest.raises(ValueError, match=">= 2"):
            pages_for_budget(pb, pb)                      # 1 page < null + 1
        with pytest.raises(ValueError, match="page_bytes"):
            pages_for_budget(10 * pb, 0)


class TestSchedulerEviction:
    def _sched(self, num_pages, batch=4, smax=64):
        a = PageAllocator(num_pages=num_pages, page_size=4)
        return ContinuousBatchingScheduler(a, batch, smax), a

    def test_exhaustion_evicts_youngest_first(self):
        sched, a = self._sched(num_pages=6)               # 5 usable
        reqs = [Request(prompt=np.arange(1, 8, dtype=np.int32),
                        max_new_tokens=30) for _ in range(3)]
        for r in reqs:
            sched.submit(r)
        admitted = sched.admissions()                     # 2 pages each
        assert [r.rid for r in admitted] == [reqs[0].rid, reqs[1].rid]
        for r in admitted:
            sched.activate(r)
        # exhaust: age both requests to 13 tokens (4 pages each, 8 > 5)
        for r in admitted:
            r.generated.extend([1] * 6)
        evicted = sched.grow()
        # the YOUNGEST (last-admitted) is preempted, copy-free
        assert evicted == [reqs[1]]
        assert reqs[1].state is RequestState.WAITING
        assert reqs[1].evictions == 1
        assert a.chain(reqs[1].rid) == []                 # pages returned
        assert sched.waiting[0] is reqs[1]                # front of queue
        a.check_consistency()

    def test_mid_decode_cancel_frees_chain(self):
        sched, a = self._sched(num_pages=16)
        r = Request(prompt=np.arange(1, 9, dtype=np.int32))
        sched.submit(r)
        for q in sched.admissions():
            sched.activate(q)
        assert a.used_pages > 0
        assert sched.cancel(r.rid)
        assert r.state is RequestState.CANCELLED
        assert a.used_pages == 0
        assert not sched.running
        assert not sched.cancel(r.rid)                    # idempotent
        a.check_consistency()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

class TestSampling:
    def _logits(self, rng, b=4, v=64):
        return jnp.asarray(rng.randn(b, v).astype(np.float32) * 3)

    def _keys(self, b, seed=0):
        return jnp.asarray(
            np.stack([np.asarray(jax.random.PRNGKey(seed + i))
                      for i in range(b)]).astype(np.uint32))

    def test_greedy_is_argmax_and_key_advances(self):
        rng = np.random.RandomState(0)
        lg = self._logits(rng)
        keys = self._keys(4)
        toks, nk = sample_tokens(lg, keys, jnp.zeros(4),
                                 jnp.zeros(4, jnp.int32), jnp.ones(4))
        assert toks.tolist() == np.argmax(np.asarray(lg), -1).tolist()
        assert not np.array_equal(np.asarray(nk), np.asarray(keys))

    def test_top_k_bounds_support(self):
        rng = np.random.RandomState(1)
        lg = self._logits(rng, b=1)
        top5 = set(np.argsort(-np.asarray(lg)[0])[:5].tolist())
        for i in range(12):
            toks, _ = sample_tokens(lg, self._keys(1, seed=i),
                                    jnp.ones(1), jnp.full((1,), 5, jnp.int32),
                                    jnp.ones(1))
            assert int(toks[0]) in top5

    def test_top_p_tiny_is_argmax(self):
        rng = np.random.RandomState(2)
        lg = self._logits(rng, b=2)
        toks, _ = sample_tokens(lg, self._keys(2), jnp.ones(2),
                                jnp.zeros(2, jnp.int32),
                                jnp.full((2,), 1e-6))
        assert toks.tolist() == np.argmax(np.asarray(lg), -1).tolist()

    def test_rows_independent(self):
        """A request's stream depends only on its own key: changing a
        batch-mate's params/logits leaves row 0 unchanged."""
        rng = np.random.RandomState(3)
        lg = self._logits(rng, b=2)
        keys = self._keys(2)
        t1, _ = sample_tokens(lg, keys, jnp.ones(2), jnp.zeros(2, jnp.int32),
                              jnp.ones(2))
        lg2 = lg.at[1].set(-lg[1])
        t2, _ = sample_tokens(lg2, keys, jnp.asarray([1.0, 0.3]),
                              jnp.asarray([0, 7], jnp.int32),
                              jnp.asarray([1.0, 0.5]))
        assert int(t1[0]) == int(t2[0])


# ---------------------------------------------------------------------------
# decode parity through the model (the Pallas kernel under interpret)
# ---------------------------------------------------------------------------

class TestDecodeParity:
    def _roundtrip(self, m, cfg, prompt, n_decode, atol):
        """Prefill + incremental decode vs the full-sequence forward (which
        runs flash/XLA attention): per-token logits must agree."""
        from paddle_tpu.parallel.train_step import functional_call

        L = cfg.num_hidden_layers
        hkv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        ps, pmax = 4, 6          # small page grid: interpret mode runs it
        dtype = m.parameters()[0]._value.dtype
        ck = jnp.zeros((L, hkv, 24, ps, d), dtype)
        cv = jnp.zeros_like(ck)
        params = [p._value for p in m.parameters()]
        seq = np.asarray(prompt, np.int32)
        full = np.asarray(
            m(paddle.to_tensor(seq[None].astype(np.int64)))._value,
            np.float32)[0]
        lp = seq.size - n_decode
        pt = np.zeros((1, pmax), np.int32)
        npages = -(-seq.size // ps)
        pt[0, :npages] = np.arange(1, npages + 1)
        cpad = 16
        ids = np.zeros((1, cpad), np.int32)
        ids[0, :lp] = seq[:lp]
        logits, cache = functional_call(
            m, params, (paddle.to_tensor(ids.astype(np.int64)),),
            dict(cache={"k": ck, "v": cv}, page_table=jnp.asarray(pt),
                 context_lens=jnp.asarray([lp], np.int32),
                 position_ids=jnp.asarray(np.arange(cpad)[None], np.int32),
                 ctx_pad=16), training=False, method="decode_forward")
        np.testing.assert_allclose(
            np.asarray(logits._value, np.float32)[0, :lp], full[:lp],
            atol=atol, rtol=atol)
        for i in range(n_decode):
            lens = lp + i
            out = functional_call(
                m, params,
                (paddle.to_tensor(np.asarray([[seq[lens - 1]]], np.int64)),),
                dict(cache=cache, page_table=jnp.asarray(pt),
                     context_lens=jnp.asarray([lens], np.int32),
                     position_ids=jnp.asarray([[lens - 1]], np.int32)),
                training=False, method="decode_forward")
            lg, cache = out
            np.testing.assert_allclose(
                np.asarray(lg._value, np.float32)[0, 0], full[lens - 1],
                atol=atol, rtol=atol)

    def test_fp32_parity(self, paged_interpret, flash_interpret):
        m, cfg = _model(num_key_value_heads=4)
        rng = np.random.RandomState(0)
        self._roundtrip(m, cfg, rng.randint(1, cfg.vocab_size, 10),
                        n_decode=2, atol=2e-4)

    def test_bf16_gqa_parity_1e3(self, paged_interpret, flash_interpret):
        """ISSUE acceptance: paged decode (interpret kernel) vs full-
        sequence flash attention, per-token logits <= 1e-3 in bf16, GQA."""
        m, cfg = _model(num_key_value_heads=2)
        m.to(dtype="bfloat16")
        rng = np.random.RandomState(1)
        self._roundtrip(m, cfg, rng.randint(1, cfg.vocab_size, 11),
                        n_decode=3, atol=1e-3)


# ---------------------------------------------------------------------------
# engine (the shared-engine fast core; system tests in test_serving_system)
# ---------------------------------------------------------------------------

class TestEngine:
    def test_greedy_parity_vs_full_forward(self, shared):
        m, cfg, eng = shared
        rng = np.random.RandomState(0)
        prompts = _prompts(rng, cfg, (5, 11, 17))
        outs = eng.generate(prompts, max_new_tokens=4)
        for p, got in zip(prompts, outs):
            assert got == _teacher_greedy(m, p, 4)

    def test_zero_decode_retraces_after_warmup(self, shared):
        m, cfg, eng = shared
        rng = np.random.RandomState(2)
        eng.generate(_prompts(rng, cfg, (5,)), max_new_tokens=2)
        eng.mark_warmup()
        # different lengths, sampling params, batch mixes — one program
        eng.generate(_prompts(rng, cfg, (9, 3, 14)), max_new_tokens=4,
                     temperature=0.7, top_k=9, top_p=0.8)
        eng.generate(_prompts(rng, cfg, (21,)), max_new_tokens=3)
        assert eng.decode_retraces_after_warmup == 0

    def test_mid_decode_cancel_frees_pages_engine(self, shared):
        m, cfg, eng = shared
        rng = np.random.RandomState(4)
        rid = eng.submit(rng.randint(1, cfg.vocab_size, 9).astype(np.int32),
                         max_new_tokens=50)
        for _ in range(3):
            eng.step()
        assert len(eng.scheduler.get(rid).generated) == 3
        assert eng.allocator.used_pages > 0
        assert eng.cancel(rid)
        assert eng.allocator.used_pages == 0
        assert not eng.step()                       # idle again
        eng.allocator.check_consistency()

    def test_pool_too_small_raises(self, shared):
        m, cfg, _ = shared
        with pytest.raises(ValueError, match="cannot hold ONE"):
            _engine(m, num_pages=4, max_seq_len=64)
        eng = _engine(m, num_pages=18, max_seq_len=64)
        with pytest.raises(ValueError, match="serving_max_seq_len"):
            eng.submit(np.arange(1, 60, dtype=np.int32), max_new_tokens=8)

    def test_rope_limit_guard(self, shared):
        m, cfg, _ = shared                          # max_pos 128
        with pytest.raises(ValueError, match="rope_max_position"):
            _engine(m, max_seq_len=256)
        m2, _ = _model(rope_max_position=256)
        eng = _engine(m2, max_seq_len=256, num_pages=128)
        assert eng.pages_per_seq == 64              # construction only

    def test_donated_params_raise_at_construction(self):
        """Serving a just-trained model whose params were donated into a
        CompiledTrainStep program must fail with the sync_params_to_model
        pointer, not an opaque deleted-array error mid-prefill."""
        m, cfg = _model()
        m.parameters()[0]._value.delete()
        with pytest.raises(ValueError, match="sync_params_to_model"):
            _engine(m)

    def test_forward_past_rope_table_raises(self):
        m, cfg = _model(max_position_embeddings=16)
        ids = paddle.to_tensor(np.ones((1, 32), np.int64))
        with pytest.raises(ValueError, match="rope_max_position"):
            m(ids)

    def test_generate_timeout_cancels_request(self, shared):
        """A /generate past its deadline emits a timeout event, frees the
        request's pages, and releases its bookkeeping (no driver thread ->
        no tokens ever land)."""
        m, cfg, eng = shared
        events = list(eng._http_generate(
            {"prompt_ids": [5, 6, 7], "max_new_tokens": 8},
            deadline=time.monotonic() - 1.0))
        assert events[-1]["error"] == "timeout"
        rid = events[-1]["rid"]
        assert eng.allocator.used_pages == 0
        assert rid not in eng.scheduler._by_rid     # released, not leaked
        assert rid not in eng._keys

    def test_client_disconnect_cancels_request(self, shared):
        """Closing a /generate stream mid-flight (GeneratorExit) must free
        the abandoned request's slot and pages immediately."""
        m, cfg, eng = shared
        import threading

        gen = eng._http_generate({"prompt_ids": [5, 6, 7],
                                  "max_new_tokens": 50},
                                 deadline=time.monotonic() + 60)
        stop = threading.Event()

        def drive():                   # the generator submits on first
            while not stop.is_set():   # next(); steps must come from a
                with eng._http_lock:   # second thread, as in serve_http
                    if not eng.scheduler.idle:
                        eng.step()
                time.sleep(0.002)

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        try:
            first = next(gen)
        finally:
            stop.set()
            t.join(timeout=10)
        assert "token" in first
        rid = first["rid"]
        gen.close()                                 # client went away
        assert eng.allocator.used_pages == 0
        assert rid not in eng.scheduler._by_rid
        assert not eng.scheduler.running


# ---------------------------------------------------------------------------
# CI guard
# ---------------------------------------------------------------------------

class TestNoPickle:
    def test_serving_package_never_imports_pickle(self):
        """Tier-1 grep guard (the elastic-checkpoint precedent): the
        serving stack — package + paged kernel — must stay pickle-free."""
        import paddle_tpu.ops.pallas.paged_attention as paged
        import paddle_tpu.serving as pkg

        files = [paged.__file__]
        root = os.path.dirname(pkg.__file__)
        files += [os.path.join(root, n) for n in os.listdir(root)
                  if n.endswith(".py")]
        offenders = []
        for path in files:
            with open(path) as f:
                src = f.read()
            for needle in ("pickle.load", "pickle.dump", "import pickle",
                           "cPickle"):
                if needle in src:
                    offenders.append(f"{os.path.basename(path)}: {needle}")
        assert not offenders, offenders


# ---------------------------------------------------------------------------
# PR 12: copy-on-write shared-prefix pages (allocator level)
# ---------------------------------------------------------------------------
class TestPrefixSharing:
    def test_kv_page_bytes_takes_cache_dtype(self):
        """Satellite regression: page sizing follows the CACHE dtype, not
        the compute dtype — an int8 KV pool halves page bytes vs bf16 (so
        a budget buys 2x the pages), and the legacy itemsize-int spelling
        keeps working."""
        bf16 = kv_page_bytes(2, 2, 16, 64, dtype_bytes=jnp.bfloat16)
        int8 = kv_page_bytes(2, 2, 16, 64, dtype_bytes=jnp.int8)
        assert bf16 == 2 * int8 == kv_page_bytes(2, 2, 16, 64, 2)
        assert kv_page_bytes(2, 2, 16, 64, np.float32) == 2 * bf16
        assert pages_for_budget(10 * int8, int8) == 2 * pages_for_budget(
            10 * int8, bf16)

    def test_match_adopt_refcount(self):
        a = PageAllocator(num_pages=16, page_size=4)
        toks = np.arange(100, 111, dtype=np.int32)       # 11 tokens
        assert a.ensure("a", toks.size)
        assert a.register_prefix("a", toks) == 2         # 2 FULL pages only
        pages, matched = a.match_prefix(toks)
        assert matched == 8 and pages == a.chain("a")[:2]
        # a diverging prefix matches only the common full pages
        other = toks.copy(); other[5] += 1
        _, m2 = a.match_prefix(other)
        assert m2 == 4
        assert a.ensure("b", 10, adopt=pages)
        assert a.chain("b")[:2] == pages
        assert all(a.ref_count(p) == 2 for p in pages)
        assert a.ref_count(a.chain("b")[2]) == 1
        a.check_consistency()
        # sharers keep the pages when one holder frees
        a.free_request("a")
        assert all(a.ref_count(p) == 1 for p in pages)
        _, m3 = a.match_prefix(toks)
        assert m3 == 8                                   # still indexed
        a.check_consistency()
        a.free_request("b")
        assert a.free_pages == a.num_pages - 1           # no leak
        assert a.match_prefix(toks) == ([], 0)           # index emptied
        a.check_consistency()

    def test_adoption_all_or_nothing_on_exhaustion(self):
        a = PageAllocator(num_pages=6, page_size=4)      # 5 usable
        toks = np.arange(1, 9, dtype=np.int32)
        assert a.ensure("a", 8)
        a.register_prefix("a", toks)
        pages, _ = a.match_prefix(toks)
        assert a.ensure("x", 4)                          # 1 page
        assert a.ensure("y", 8)                          # 2 pages -> 0 free
        # adopting 2 shared + needing 2 fresh must fail atomically
        assert not a.ensure("b", 16, adopt=pages)
        assert a.chain("b") == []
        assert all(a.ref_count(p) == 1 for p in pages)
        a.check_consistency()

    def test_cow_swaps_writer_only(self):
        a = PageAllocator(num_pages=16, page_size=4)
        toks = np.arange(1, 9, dtype=np.int32)
        assert a.ensure("a", 8)
        a.register_prefix("a", toks)
        pages, _ = a.match_prefix(toks)
        assert a.ensure("b", 9, adopt=pages)             # shares 2, owns 1
        before_a = a.chain("a")
        copies = a.make_writable("b", 7, 8)              # page idx 1..2
        assert len(copies) == 1                          # only idx 1 shared
        (src, dst), = copies
        assert src == before_a[1] and a.chain("b")[1] == dst
        assert a.chain("a") == before_a                  # sharer untouched
        assert a.ref_count(src) == 1 and a.ref_count(dst) == 1
        assert a.cow_copies == 1
        # the index entry stays with the ORIGINAL page
        p2, m2 = a.match_prefix(toks)
        assert m2 == 8 and p2 == before_a[:2]
        a.check_consistency()
        # exhaustion: all-or-nothing None, nothing changed
        for i in range(a.free_pages):
            assert a.ensure(f"f{i}", 4)
        assert a.ensure("c", 8, adopt=a.match_prefix(toks)[0])
        assert a.make_writable("c", 0, 7) is None
        a.check_consistency()

    def test_aliasing_fuzz_with_shared_cow_chains(self):
        """ISSUE acceptance: the PR-9 aliasing fuzz extended with prefix
        adoption, registration and copy-on-write — check_consistency()
        (refcounts == holding chains, free/live partition, index points
        at live pages) must hold after EVERY op, and a full teardown
        leaves zero allocated pages."""
        a = PageAllocator(num_pages=48, page_size=2)
        rng = np.random.RandomState(7)
        live: dict[int, np.ndarray] = {}
        corpus = [rng.randint(1, 9, 12).astype(np.int32) for _ in range(4)]
        for step in range(400):
            rid = int(rng.randint(10))
            op = rng.rand()
            if rid in live and op < 0.25:
                a.free_request(rid)
                del live[rid]
            elif rid not in live:
                base = corpus[rng.randint(len(corpus))]
                n = int(rng.randint(2, base.size + 1))
                toks = base[:n].copy()
                if rng.rand() < 0.3:
                    toks[-1] = rng.randint(1, 9)         # diverge the tail
                pages, matched = a.match_prefix(toks)
                if a.ensure(rid, toks.size, adopt=pages or None):
                    live[rid] = toks
                    a.register_prefix(rid, toks)
            else:
                toks = live[rid]
                if rng.rand() < 0.5:
                    grown = np.concatenate(
                        [toks, rng.randint(1, 9, 2).astype(np.int32)])
                    if a.ensure(rid, grown.size):
                        live[rid] = grown
                else:
                    a.make_writable(rid, max(toks.size - 2, 0),
                                    toks.size - 1)
            a.check_consistency()
        for rid in list(live):
            a.free_request(rid)
        a.check_consistency()
        assert a.free_pages == a.num_pages - 1           # no page leaked


class TestSharedChainEviction:
    def test_evict_shared_chain_requeues_without_freeing_sharers(self):
        """Satellite: evicting a request whose chain holds SHARED pages
        re-queues it (front, WAITING) while every sharer keeps its pages
        — only the victim's exclusive refs return to the free list."""
        a = PageAllocator(num_pages=10, page_size=4)     # 9 usable
        s = ContinuousBatchingScheduler(a, max_batch=4, max_seq_len=64,
                                        prefix_sharing=True)
        toks = np.arange(1, 9, dtype=np.int32)           # 2 full pages
        holder = Request(prompt=toks, max_new_tokens=4)
        victim = Request(prompt=toks, max_new_tokens=4)
        s.submit(holder); s.submit(victim)
        admitted = s.admissions(limit=1)
        assert admitted == [holder]
        a.register_prefix(holder.rid, toks)              # engine's step
        s.activate(holder)
        admitted = s.admissions(limit=1)
        assert admitted == [victim] and victim.matched_tokens == 8
        s.activate(victim)
        shared = a.chain(holder.rid)[:2]
        assert a.chain(victim.rid)[:2] == shared
        free_before = a.free_pages
        # exhaust the pool so grow() must evict the YOUNGEST (the sharer)
        assert a.ensure("hog", 4 * free_before)
        holder.generated = [1]                           # forces growth
        evicted = s.grow()
        assert evicted == [victim]
        assert victim.state == RequestState.WAITING
        assert s.waiting[0] is victim and victim.matched_tokens == 0
        # the sharers' pages survived the eviction
        assert a.chain(holder.rid)[:2] == shared
        assert all(a.ref_count(p) == 1 for p in shared)
        a.check_consistency()


# ---------------------------------------------------------------------------
# PR 12: speculative decoding (engine level; reference decode path)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def spec_shared(shared):
    """ONE speculative engine (K=3, sharing on) over the module's shared
    model — extra verify windows compile on demand and are cached per K."""
    m, cfg, _ = shared
    return m, cfg, _engine(m, spec_k=3, prefix_sharing=True)


def _aligned(*engines, seq=1000):
    """Pin the per-engine submission counters so PRNG key streams match
    across engines (keys are keyed by submission ORDER)."""
    for e in engines:
        e._submit_seq = seq


class TestSpeculativeDecoding:
    def test_greedy_stream_bit_equal_and_multi_token_steps(self, shared,
                                                           spec_shared):
        """ISSUE acceptance: greedy streams with speculation + prefix
        sharing ON are bit-equal to the PR-9 plain-decode engine, while
        committing > 1 token per dispatch."""
        m, cfg, base = shared
        _, _, spec = spec_shared
        rng = np.random.RandomState(11)
        sysp = rng.randint(1, cfg.vocab_size, 12).astype(np.int32)
        prompts = [np.concatenate([sysp, t]) for t in
                   _prompts(rng, cfg, (3, 6, 5))]
        ref = base.generate(prompts, max_new_tokens=12)
        spec.reset_stats()
        out = spec.generate(prompts, max_new_tokens=12)
        assert out == ref
        assert spec.accepted_tokens_per_step > 1.0
        assert spec.prefix_hit_rate > 0.0                # sysp pages shared
        spec.allocator.check_consistency()
        assert spec.allocator.free_pages == spec.allocator.num_pages - 1

    def test_temperature_stream_bit_equal(self, shared, spec_shared):
        """Sampled (temp/top-k/top-p) streams are bit-equal too: the
        verify frame draws position i with the KEY plain decode would
        hold after i commits, and acceptance == sampled-token equality."""
        m, cfg, base = shared
        _, _, spec = spec_shared
        rng = np.random.RandomState(12)
        prompts = _prompts(rng, cfg, (5, 9, 7))
        _aligned(base, spec)
        ref = base.generate(prompts, max_new_tokens=10, temperature=0.8,
                            top_k=24, top_p=0.9)
        out = spec.generate(prompts, max_new_tokens=10, temperature=0.8,
                            top_k=24, top_p=0.9)
        assert out == ref

    def test_k1_degenerate_matches_plain_decode(self, shared, spec_shared):
        """ISSUE acceptance: K=1 (one draft + bonus) reproduces the PR-9
        stream exactly and never over-commits past the budget."""
        m, cfg, base = shared
        _, _, spec = spec_shared
        rng = np.random.RandomState(13)
        prompts = _prompts(rng, cfg, (4, 8))
        ref = base.generate(prompts, max_new_tokens=9)
        spec.configure_speculation(spec_k=1)
        try:
            out = spec.generate(prompts, max_new_tokens=9)
        finally:
            spec.configure_speculation(spec_k=3)
        assert out == ref
        assert all(len(o) == 9 for o in out)

    def test_zero_retraces_across_k(self, shared, spec_shared):
        """ISSUE acceptance: after each verify window compiles once,
        stepping ANY warmed K (and toggling between them) never
        retraces — per-request windows ride the signature as arrays."""
        m, cfg, spec = spec_shared
        rng = np.random.RandomState(14)
        for k in (2, 3):                                 # warm both
            spec.configure_speculation(spec_k=k)
            spec.generate(_prompts(rng, cfg, (5,)), max_new_tokens=6)
        spec.mark_warmup()
        for k in (3, 2, 3):
            spec.configure_speculation(spec_k=k)
            spec.generate(_prompts(rng, cfg, (6, 4)), max_new_tokens=8,
                          temperature=0.7)
        assert spec.decode_retraces_after_warmup == 0
        spec.configure_speculation(spec_k=3)

    def test_toggle_spec_on_mid_flight_reseeds_proposer(self, shared,
                                                        spec_shared):
        """Turning speculation ON while requests are live must reseed the
        proposer from each committed stream (plain decode neither seeds
        nor feeds it): the continued stream stays bit-equal and the live
        request drafts from real tables, not missing state."""
        m, cfg, base = shared
        _, _, spec = spec_shared
        rng = np.random.RandomState(15)
        prompt = _prompts(rng, cfg, (7,))[0]
        ref = base.generate([prompt], max_new_tokens=12)[0]
        spec.configure_speculation(spec_k=0)
        try:
            rid = spec.submit(prompt, max_new_tokens=12)
            for _ in range(4):                   # plain-decode opening
                spec.step()
            assert rid not in spec._proposer._state
            spec.configure_speculation(spec_k=3)
            assert rid in spec._proposer._state  # reseeded mid-flight
            spec.run_until_idle()
        finally:
            spec.configure_speculation(spec_k=3)
        out = list(spec.scheduler.get(rid).generated)
        spec.release(rid)
        assert out == ref
        spec.allocator.check_consistency()

    def test_cow_write_leaves_sharer_bytes_identical(self, shared):
        """ISSUE acceptance: a full-prefix admission adopts every page;
        its first decode rewrite triggers copy-on-write, and the
        sharer's pages are BYTE-identical afterwards."""
        m, cfg, _ = shared
        eng = _engine(m, spec_k=2, prefix_sharing=True)
        rng = np.random.RandomState(15)
        prompt = rng.randint(1, cfg.vocab_size, 16).astype(np.int32)
        # A outlives B (large budget) so its chain still holds the shared
        # pages while B copy-on-writes
        ra = eng.submit(prompt, max_new_tokens=40)
        eng.step()                                       # admit+prefill A
        a_pages = eng.allocator.chain(ra)[:4]
        ck_before = np.asarray(eng._ck[:, :, a_pages])
        cv_before = np.asarray(eng._cv[:, :, a_pages])
        rb = eng.submit(prompt, max_new_tokens=8)        # full 4-page match
        req_b = eng.scheduler.get(rb)
        while not req_b.finished:
            eng.step()
        assert req_b.matched_tokens == 16                # prefill skipped
        assert eng.allocator.cow_copies >= 1
        assert eng.allocator.chain(ra)[:4] == a_pages    # A untouched
        np.testing.assert_array_equal(
            np.asarray(eng._ck[:, :, a_pages]), ck_before)
        np.testing.assert_array_equal(
            np.asarray(eng._cv[:, :, a_pages]), cv_before)
        eng.cancel(ra)
        eng.allocator.check_consistency()
        # B's stream equals A's prefix (same prompt, greedy; A had the
        # larger budget so it is the longer stream)
        req_a = eng.scheduler.get(ra)
        assert req_a.generated[:len(req_b.generated)] == req_b.generated

    def test_verify_mismatch_chaos_degrades_to_plain_decode(self, shared,
                                                            spec_shared):
        """Satellite: the serving.spec.verify_mismatch fault point forces
        FULL rejection every step — the engine must degrade to one
        committed token per dispatch with the exact same stream, not
        wedge."""
        from paddle_tpu.distributed.resilience import faults

        m, cfg, base = shared
        _, _, spec = spec_shared
        rng = np.random.RandomState(16)
        prompts = _prompts(rng, cfg, (5, 7))
        ref = base.generate(prompts, max_new_tokens=8)
        spec.reset_stats()
        faults.arm("serving.spec.verify_mismatch", mode="always")
        try:
            out = spec.generate(prompts, max_new_tokens=8)
        finally:
            faults.disarm("serving.spec.verify_mismatch")
        assert out == ref
        assert faults.fired("serving.spec.verify_mismatch") > 0
        assert spec.accepted_tokens_per_step == 1.0      # plain decode rate

    def test_prefix_skip_prefill_and_stats(self, shared):
        """A second same-prompt admission adopts the registered pages:
        prefill runs zero tail chunks, the hit rate reflects it, and
        stats() carries the PR-12 fields the router/bench consume."""
        m, cfg, _ = shared
        eng = _engine(m, spec_k=0, prefix_sharing=True)
        rng = np.random.RandomState(17)
        prompt = rng.randint(1, cfg.vocab_size, 16).astype(np.int32)
        eng.generate([prompt], max_new_tokens=4)
        # second request arrives while nothing shares -> index emptied on
        # release, so submit BOTH to overlap
        eng.reset_stats()
        o = eng.generate([prompt, prompt], max_new_tokens=4)
        assert o[0] == o[1]
        assert eng.prefix_hit_rate >= 0.4                # 16 of 32+ tokens
        st = eng.stats()
        for key in ("accepted_tokens_per_step", "prefix_hit_rate",
                    "cow_copies", "spec_k", "draft_ms_total"):
            assert key in st
        eng.allocator.check_consistency()


class TestSpeculativeInterpretKernel:
    """ISSUE acceptance: speculative streams bit-equal to plain decode ON
    THE INTERPRET KERNEL PATH (the exact TPU decode/verify kernel — the
    paged_interpret fixture pins it; prefill keeps the engine's normal
    dispatch), fp32 + bf16 GQA. Small engines bound the interpret grid."""

    def _run(self, dtype, kv_heads, paged_on):
        m, cfg = _model(num_key_value_heads=kv_heads)
        if dtype == "bfloat16":
            m.to(dtype="bfloat16")
        kw = dict(page_size=4, num_pages=24, decode_batch=2,
                  prefill_chunk=8, max_seq_len=16)
        rng = np.random.RandomState(21)
        prompts = _prompts(rng, cfg, (5, 7))
        base = ServingEngine(m, ServingConfig(**kw, spec_k=0,
                                              prefix_sharing=False))
        spec = ServingEngine(m, ServingConfig(**kw, spec_k=2,
                                              prefix_sharing=True))
        _aligned(base, spec)
        ref = base.generate(prompts, max_new_tokens=5, temperature=0.5,
                            top_k=16)
        out = spec.generate(prompts, max_new_tokens=5, temperature=0.5,
                            top_k=16)
        assert out == ref
        assert spec.decode_traces >= 1

    def test_fp32(self, paged_interpret):
        self._run("float32", 4, True)

    @pytest.mark.slow
    def test_bf16_gqa(self, paged_interpret):
        self._run("bfloat16", 2, True)
