"""Serving engine, system tier (slow: each engine costs a fresh XLA
compile): packed-batch == per-request loops, copy-free eviction
equivalence, the static-batch baseline, and the hardened HTTP front-end
(streaming /generate, concurrency, bounded queue, Content-Length caps)."""
import io
import json
import socket
import threading
import time

import numpy as np
import pytest

from test_serving import _engine, _model, _prompts, _teacher_greedy


@pytest.fixture(scope="module")
def shared():
    m, cfg = _model()
    return m, cfg, _engine(m)


class TestEngineSystem:
    def test_packed_decode_equals_per_request_loops(self, shared):
        """ISSUE acceptance: one packed multi-request decode step produces
        exactly what isolated per-request decode loops produce (the shared
        4-slot engine vs a 1-slot engine)."""
        m, cfg, eng = shared
        rng = np.random.RandomState(1)
        prompts = _prompts(rng, cfg, (6, 13, 4, 9))
        packed = eng.generate(prompts, max_new_tokens=5)
        one = _engine(m, decode_batch=1)
        per_req = [one.generate([p], max_new_tokens=5)[0] for p in prompts]
        assert packed == per_req

    def test_eviction_recovers_same_greedy_tokens(self, shared):
        """Copy-free eviction = preempt-by-recomputation: a starved pool
        must still produce the un-starved greedy streams (vs the full-
        forward teacher)."""
        m, cfg, _ = shared
        rng = np.random.RandomState(3)
        prompts = _prompts(rng, cfg, (8, 8, 8))
        starved_eng = _engine(m, num_pages=10, decode_batch=3,
                              max_seq_len=32)
        # submit/run directly (generate() releases finished requests, and
        # this test needs the per-request eviction counters afterwards)
        rids = [starved_eng.submit(p, max_new_tokens=12) for p in prompts]
        starved_eng.run_until_idle()
        reqs = [starved_eng.scheduler.get(r) for r in rids]
        starved = [list(r.generated) for r in reqs]
        assert starved == [_teacher_greedy(m, p, 12) for p in prompts]
        assert sum(r.evictions for r in reqs) > 0  # the pool DID starve
        starved_eng.allocator.check_consistency()
        assert starved_eng.allocator.used_pages == 0

    def test_static_batch_matches_greedy(self, shared):
        m, cfg, eng = shared
        rng = np.random.RandomState(5)
        prompts = _prompts(rng, cfg, (5, 9, 3))
        cont = eng.generate(prompts, max_new_tokens=4)
        reqs = eng.static_batch_generate(prompts, 4)
        assert [r.generated for r in reqs] == cont

    def test_sampled_streams_reproducible(self):
        m, cfg = _model()
        rng = np.random.RandomState(6)
        prompts = _prompts(rng, cfg, (5, 11))

        def run():
            return _engine(m).generate(prompts, max_new_tokens=6,
                                       temperature=0.9, top_k=50,
                                       top_p=0.95)

        assert run() == run()


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

def _post_raw(port, path, body: bytes, headers=None, read_all=True):
    """Raw-socket POST so we can observe early rejections (a urllib client
    dies on the broken pipe when the server 413s before the body lands)."""
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    try:
        head = [f"POST {path} HTTP/1.1", "Host: x"]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {v}")
        s.sendall(("\r\n".join(head) + "\r\n\r\n").encode())
        try:
            s.sendall(body)
        except (BrokenPipeError, ConnectionResetError):
            pass                                   # server rejected early
        s.shutdown(socket.SHUT_WR)
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
            if not read_all and b"\r\n\r\n" in b"".join(chunks):
                break
        return b"".join(chunks)
    finally:
        s.close()


class TestHTTPFrontend:
    @pytest.fixture(scope="class")
    def engine_server(self):
        m, cfg = _model()
        eng = _engine(m)
        srv = eng.serve_http(0, block=False)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield eng, srv.server_address[1], cfg
        eng.shutdown_http()

    def test_streaming_generate_and_parity(self, engine_server):
        eng, port, cfg = engine_server
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, cfg.vocab_size, 7).tolist()
        body = json.dumps({"prompt_ids": prompt,
                           "max_new_tokens": 5}).encode()
        resp = _post_raw(port, "/generate", body,
                         {"Content-Length": len(body)})
        head, payload = resp.split(b"\r\n\r\n", 1)
        assert b"200" in head.split(b"\r\n")[0]
        events = [json.loads(l) for l in payload.strip().splitlines()]
        toks = [e["token"] for e in events if "token" in e]
        assert events[-1]["done"] and events[-1]["tokens"] == 5
        assert toks == _teacher_greedy(eng.model, np.asarray(prompt), 5)

    def test_concurrent_streams_interleave(self, engine_server):
        eng, port, cfg = engine_server
        results = {}

        def call(i, n):
            body = json.dumps({"prompt_ids": [3 + i, 7, 11],
                               "max_new_tokens": n}).encode()
            resp = _post_raw(port, "/generate", body,
                             {"Content-Length": len(body)})
            payload = resp.split(b"\r\n\r\n", 1)[1]
            results[i] = [json.loads(l)
                          for l in payload.strip().splitlines()]

        threads = [threading.Thread(target=call, args=(i, 4 + i))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(results[i][-1]["tokens"] == 4 + i for i in range(3))

    def test_bad_payload_yields_error_event(self, engine_server):
        eng, port, _ = engine_server
        body = json.dumps({"max_new_tokens": 2}).encode()   # no prompt_ids
        resp = _post_raw(port, "/generate", body,
                         {"Content-Length": len(body)})
        payload = resp.split(b"\r\n\r\n", 1)[1]
        events = [json.loads(l) for l in payload.strip().splitlines()]
        assert "error" in events[-1] and "KeyError" in events[-1]["error"]

    def test_content_length_cap_and_missing(self, engine_server):
        _, port, _ = engine_server
        resp = _post_raw(port, "/generate", b"x" * 64,
                         {"Content-Length": 9 << 20}, read_all=False)
        assert b"413" in resp.split(b"\r\n")[0]
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.sendall(b"POST /generate HTTP/1.1\r\nHost: x\r\n\r\n")
        resp = s.recv(65536)
        s.close()
        assert b"411" in resp.split(b"\r\n")[0]

    def test_unknown_path_404(self, engine_server):
        _, port, _ = engine_server
        resp = _post_raw(port, "/nope", b"{}", {"Content-Length": 2})
        assert b"404" in resp.split(b"\r\n")[0]

    def test_bounded_queue_503(self):
        """queue_limit in-flight handlers -> the next connection is turned
        away immediately instead of head-of-line blocking."""
        from paddle_tpu.inference.serve import build_http_server

        release = threading.Event()

        def slow_gen(payload, deadline):
            release.wait(timeout=30)
            yield {"done": True}

        srv = build_http_server(0, generate_fn=slow_gen, queue_limit=1,
                                timeout_s=30)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            body = b"{}"
            hold = threading.Thread(
                target=_post_raw, args=(port, "/generate", body),
                kwargs={"headers": {"Content-Length": 2}}, daemon=True)
            hold.start()
            time.sleep(0.3)                       # let it occupy the slot
            resp = _post_raw(port, "/generate", body,
                             {"Content-Length": 2})
            assert b"503" in resp.split(b"\r\n")[0]
        finally:
            release.set()
            srv.shutdown()
            srv.server_close()

    def test_threading_run_endpoint_still_serves(self):
        from paddle_tpu.inference.serve import build_http_server

        def run_fn(arrays):
            return [arrays[0] * 2]

        srv = build_http_server(0, run_fn=run_fn)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            buf = io.BytesIO()
            np.savez(buf, inp0=np.arange(4.0))
            body = buf.getvalue()
            resp = _post_raw(port, "/run", body,
                             {"Content-Length": len(body)})
            payload = resp.split(b"\r\n\r\n", 1)[1]
            with np.load(io.BytesIO(payload)) as z:
                np.testing.assert_array_equal(z["out0"], np.arange(4.0) * 2)
        finally:
            srv.shutdown()
            srv.server_close()
