"""Static-graph user API: Program recording + Executor replay.

Reference: python/paddle/static (ProgramDesc build under static mode,
base/executor.py:1637 Executor.run → StandaloneExecutor/PirInterpreter).
TPU-native: instructions recorded at the apply_op seam replay as ONE jitted
XLA program (paddle_tpu/static/graph.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import static


def _batch(n=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 4).astype("float32")
    y = (x[:, :1].sum(axis=1, keepdims=True) > 0).astype("int64")
    return x, y


def test_program_records_and_trains():
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "int64")
        h = static.nn.fc(x, 16, activation="relu")
        out = static.nn.fc(h, 3)
        loss = F.cross_entropy(out, y).mean()
        params = [t for t in main.params.values() if not t.stop_gradient]
        opt = paddle.optimizer.Adam(0.05, parameters=params)
        opt.minimize(loss)

    assert main.num_ops() > 0 and "x" in main.feed_vars and "y" in main.feed_vars
    exe = static.Executor()
    assert exe.run(startup) == []  # params init eagerly; startup is a no-op

    xb, yb = _batch()
    losses = []
    for _ in range(30):
        lv, = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 0.5 * losses[0], losses

    # eval clone: no optimizer, shares instructions/params, fetchable
    test_prog = main.clone(for_test=True)
    ov, = exe.run(test_prog, feed={"x": xb, "y": yb}, fetch_list=[out])
    assert ov.shape == (16, 3)
    assert np.argmax(ov, axis=1).reshape(-1, 1).mean() >= 0  # sane numbers

    # a different batch size re-jits the same polymorphic replay
    xb5, yb5 = _batch(5, seed=1)
    ov5, = exe.run(test_prog, feed={"x": xb5, "y": yb5}, fetch_list=[out])
    assert ov5.shape == (5, 3)


def test_static_matches_eager_losses():
    """Same init, same data, same optimizer: recorded-replay training must
    produce the same loss sequence as eager tape training."""

    def build():
        paddle.seed(7)
        return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3))

    xb, yb = _batch(8, seed=3)

    # eager twin
    model_e = build()
    opt_e = paddle.optimizer.SGD(0.1, parameters=model_e.parameters())
    eager_losses = []
    for _ in range(5):
        out = model_e(paddle.to_tensor(xb))
        loss = F.cross_entropy(out, paddle.to_tensor(yb)).mean()
        loss.backward()
        opt_e.step()
        opt_e.clear_grad()
        eager_losses.append(float(loss))

    # static twin (fresh but identically seeded params)
    main = static.Program()
    with static.program_guard(main):
        model_s = build()
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "int64")
        loss_v = F.cross_entropy(model_s(x), y).mean()
        opt_s = paddle.optimizer.SGD(0.1, parameters=model_s.parameters())
        opt_s.minimize(loss_v)

    exe = static.Executor()
    static_losses = [float(exe.run(main, feed={"x": xb, "y": yb},
                                   fetch_list=[loss_v])[0]) for _ in range(5)]
    np.testing.assert_allclose(static_losses, eager_losses, rtol=2e-5, atol=2e-6)

    # static updates write back into the live parameters
    np.testing.assert_allclose(
        np.asarray(model_s.state_dict()["0.weight"]._value),
        np.asarray(model_e.state_dict()["0.weight"]._value), rtol=2e-5, atol=2e-6)


def test_enable_static_default_program():
    paddle.seed(0)
    from paddle_tpu.static.graph import _reset_default_programs

    _reset_default_programs()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
        x = static.data("x", [None, 4], "float32")
        out = static.nn.fc(x, 2)
        exe = static.Executor()
        exe.run(static.default_startup_program())
        ov, = exe.run(static.default_main_program(),
                      feed={"x": np.ones((3, 4), "float32")}, fetch_list=[out])
        assert ov.shape == (3, 2)
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_static_dropout_refreshes_per_run():
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 64], "float32")
        out = F.dropout(x, 0.5, training=True)
    exe = static.Executor()
    feed = {"x": np.ones((2, 64), "float32")}
    a, = exe.run(main, feed=feed, fetch_list=[out])
    b, = exe.run(main, feed=feed, fetch_list=[out])
    # masks must differ across runs (frozen-key replay would make them equal)
    assert (a != b).any()
    # and the dropout still zeroes ~half
    assert 0.2 < (a == 0).mean() < 0.8


def test_for_test_clone_is_deterministic():
    """clone(for_test=True) neutralizes dropout: identical feeds give
    identical outputs (reference Program.clone(for_test) semantics)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 32], "float32")
        out = F.dropout(x * 2.0, 0.5, training=True)
    test_prog = main.clone(for_test=True)
    exe = static.Executor()
    feed = {"x": np.ones((2, 32), "float32")}
    a, = exe.run(test_prog, feed=feed, fetch_list=[out])
    b, = exe.run(test_prog, feed=feed, fetch_list=[out])
    np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(a, 2.0)  # identity, not a frozen mask


def test_fc_flattens_with_polymorphic_batch():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 8], "float32")
        out = static.nn.fc(x, 5)
    exe = static.Executor()
    ov, = exe.run(main, feed={"x": np.ones((4, 3, 8), "float32")}, fetch_list=[out])
    assert ov.shape == (4, 5)


def test_batch_norm_stats_update_across_runs():
    """BN running statistics recorded as writeback instructions keep their
    EMA moving under Executor.run (not frozen at build-time values)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 6], "float32")
        bn = nn.BatchNorm1D(6, momentum=0.5)
        out = bn(x)
    exe = static.Executor()
    rm0 = np.asarray(bn._mean._value).copy()
    rs = np.random.RandomState(0)
    feed = {"x": (rs.randn(32, 6) * 3 + 5).astype("float32")}
    for _ in range(8):
        exe.run(main, feed=feed, fetch_list=[out])
    rm = np.asarray(bn._mean._value)
    rv = np.asarray(bn._variance._value)
    assert not np.allclose(rm, rm0)
    # after 8 runs at momentum 0.5 the EMA is within ~0.4% of batch stats
    np.testing.assert_allclose(rm, feed["x"].mean(0), rtol=0.1, atol=0.1)
    np.testing.assert_allclose(rv, feed["x"].var(0), rtol=0.15, atol=0.15)
    # eval clone does not move the stats
    test_prog = main.clone(for_test=True)
    exe.run(test_prog, feed=feed, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(bn._mean._value), rm)


def test_fetch_foreign_var_rejected():
    main, other = static.Program(), static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        out = x * 2.0
    with static.program_guard(other):
        x2 = static.data("x", [2, 2], "float32")
        out2 = x2 + 1.0
    exe = static.Executor()
    with pytest.raises(ValueError, match="fetch_list"):
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")}, fetch_list=[out2])
    with pytest.raises(ValueError, match="missing feeds"):
        exe.run(main, feed={}, fetch_list=[out])


def test_save_load_program_params(tmp_path):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        out = static.nn.fc(x, 2)
    exe = static.Executor()
    feed = {"x": np.ones((2, 4), "float32")}
    before, = exe.run(main, feed=feed, fetch_list=[out])
    static.save(main, str(tmp_path / "ckpt"))
    # clobber params, reload, outputs restored
    for t in main.params.values():
        t._set_value(np.zeros_like(np.asarray(t._value)))
    zeroed, = exe.run(main, feed=feed, fetch_list=[out])
    assert not np.allclose(zeroed, before)
    static.load(main, str(tmp_path / "ckpt"))
    after, = exe.run(main, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(after, before, rtol=1e-6)


def test_save_inference_model_roundtrip(tmp_path):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        h = static.nn.fc(x, 8, activation="relu")
        out = static.nn.fc(h, 3)
    exe = static.Executor()
    prefix = str(tmp_path / "infer")
    static.save_inference_model(prefix, [x], [out], exe)

    runnable, feed_names, fetch_targets = static.load_inference_model(prefix, exe)
    assert feed_names == ["x"]
    xb = np.random.RandomState(0).randn(5, 4).astype("float32")
    got = runnable(xb)
    got0 = np.asarray((got[0] if isinstance(got, (list, tuple)) else got)._value)
    ref, = exe.run(main, feed={"x": xb}, fetch_list=[out])
    np.testing.assert_allclose(got0, ref, rtol=1e-5, atol=1e-6)

    # the same artifact serves through paddle.inference
    pred = paddle.inference.create_predictor(paddle.inference.Config(prefix))
    h0 = pred.get_input_handle(pred.get_input_names()[0])
    h0.copy_from_cpu(xb)
    pred.run()
    out_np = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out_np, ref, rtol=1e-5, atol=1e-6)
