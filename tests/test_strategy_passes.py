"""DistributedStrategy toggles are behavior, not decoration
(reference fleet/meta_optimizers/: gradient_merge, amp, recompute)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet


def _fresh_fleet(strategy):
    fleet.init(is_collective=True, strategy=strategy)


def test_gradient_merge_accumulates_k_steps():
    s = fleet.DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2, "avg": True}
    _fresh_fleet(s)
    paddle.seed(0)
    m = nn.Linear(2, 1, bias_attr=False)
    w0 = np.asarray(m.weight._value).copy()
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=1.0, parameters=m.parameters()), s)

    x = paddle.to_tensor(np.ones((1, 2), np.float32))
    # step 1: no update yet
    m(x).sum().backward()
    opt.step()
    opt.clear_grad()
    np.testing.assert_allclose(np.asarray(m.weight._value), w0)
    # step 2: one update with the AVERAGED merged grad (= single-step grad)
    m(x).sum().backward()
    opt.step()
    opt.clear_grad()
    np.testing.assert_allclose(np.asarray(m.weight._value), w0 - 1.0,
                               rtol=1e-6)


def test_amp_o2_strategy_casts_params():
    s = fleet.DistributedStrategy()
    s.amp = True
    s.amp_configs = {"use_pure_fp16": True, "dtype": "bfloat16"}
    _fresh_fleet(s)
    paddle.seed(0)
    m = nn.Linear(4, 4)
    dm = fleet.distributed_model(m)
    import jax.numpy as jnp

    assert all(p._value.dtype == jnp.bfloat16 for p in dm.parameters()
               if jnp.issubdtype(p._value.dtype, jnp.floating) or True)


def test_recompute_strategy_wraps_named_layers():
    s = fleet.DistributedStrategy()
    s.recompute = True
    s.recompute_configs = {"checkpoints": ["block"]}
    _fresh_fleet(s)
    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = nn.Sequential(nn.Linear(4, 8), nn.Tanh())
            self.head = nn.Linear(8, 2)

        def forward(self, x):
            return self.head(self.block(x))

    m = Net()
    dm = fleet.distributed_model(m)
    assert getattr(m.block, "_recompute_wrapped", False)
    assert not getattr(m.head, "_recompute_wrapped", False)
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    out = dm(x)
    loss = out.sum()
    loss.backward()
    # grads flow through the recomputed block
    assert m.block[0].weight.grad is not None


def test_recompute_matches_plain_backward():
    """recompute(layer, x): identical loss AND weight grads vs the plain
    path (reference recompute.py contract), with remat in between."""
    from paddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(0)
    block = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 4))
    x_np = np.random.RandomState(0).randn(3, 4).astype(np.float32)

    x1 = paddle.to_tensor(x_np, stop_gradient=False)
    loss1 = block(x1).sum()
    loss1.backward()
    g_plain = [np.asarray(p.grad._value).copy() for p in block.parameters()]
    gx_plain = np.asarray(x1.grad._value).copy()
    for p in block.parameters():
        p.clear_grad()

    x2 = paddle.to_tensor(x_np, stop_gradient=False)
    loss2 = recompute(block, x2).sum()
    loss2.backward()
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-6)
    for p, g in zip(block.parameters(), g_plain):
        np.testing.assert_allclose(np.asarray(p.grad._value), g,
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(x2.grad._value), gx_plain,
                               rtol=1e-5, atol=1e-6)
