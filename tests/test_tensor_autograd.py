"""Eager tensor + autograd engine tests (reference analog: test/legacy_test
tensor/backward units, OpTest.check_grad numeric-vs-analytic)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def t(arr, sg=True):
    return paddle.to_tensor(np.asarray(arr, np.float32), stop_gradient=sg)


class TestTensorBasics:
    def test_to_tensor_numpy_roundtrip(self):
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert x.shape == [2, 2]
        assert x.dtype == paddle.float32
        np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])

    def test_dtypes(self):
        assert paddle.to_tensor([1, 2]).dtype == paddle.int64
        assert paddle.to_tensor([1.0]).dtype == paddle.float32
        assert paddle.to_tensor([True]).dtype.name == "bool"
        x = paddle.to_tensor([1.0], dtype="bfloat16")
        assert x.dtype == paddle.bfloat16

    def test_arith_dunders(self):
        a, b = t([1.0, 2.0]), t([3.0, 4.0])
        np.testing.assert_allclose((a + b).numpy(), [4, 6])
        np.testing.assert_allclose((a * b).numpy(), [3, 8])
        np.testing.assert_allclose((b / a).numpy(), [3, 2])
        np.testing.assert_allclose((a - 1).numpy(), [0, 1])
        np.testing.assert_allclose((2 - a).numpy(), [1, 0])
        np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((-a).numpy(), [-1, -2])

    def test_getitem_setitem(self):
        x = t(np.arange(12).reshape(3, 4))
        np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
        np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
        x[0, 0] = 99.0
        assert float(x[0, 0]) == 99.0

    def test_item_and_shape(self):
        x = t(3.5)
        assert x.item() == 3.5
        assert x.ndim == 0
        assert t([[1, 2]]).size == 2


class TestAutograd:
    def test_simple_backward(self):
        x = t([2.0, 3.0], sg=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_chain(self):
        x = t([1.0], sg=False)
        y = paddle.exp(paddle.sin(x))
        y.backward()
        expect = np.exp(np.sin(1.0)) * np.cos(1.0)
        np.testing.assert_allclose(x.grad.numpy(), [expect], rtol=1e-6)

    def test_branching_accumulation(self):
        x = t([1.0, 2.0], sg=False)
        a = x * 2
        b = x * 3
        (a + b).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_grad_accumulates_across_backwards(self):
        x = t([1.0], sg=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_stop_gradient_blocks(self):
        x = t([1.0], sg=False)
        y = t([2.0], sg=True)
        (x * y).sum().backward()
        assert y.grad is None
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_detach(self):
        x = t([1.0], sg=False)
        d = (x * 2).detach()
        assert d.stop_gradient
        z = x * 2 + d
        z.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_no_grad_context(self):
        x = t([1.0], sg=False)
        with paddle.no_grad():
            y = x * 2
        assert y._grad_node is None

    def test_retain_graph(self):
        x = t([1.0], sg=False)
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])

    def test_double_backward_without_retain_raises(self):
        x = t([1.0], sg=False)
        y = (x * x).sum()
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_non_scalar_backward_with_grad(self):
        x = t([1.0, 2.0], sg=False)
        y = x * 3
        y.backward(grad_tensor=paddle.to_tensor(np.array([1.0, 10.0], np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_multi_output_op(self):
        x = t(np.arange(6).reshape(2, 3), sg=False)
        a, b = paddle.split(x, 2, axis=0)
        (a.sum() * 2 + b.sum() * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [3, 3, 3]])

    def test_matmul_grad_matches_numeric(self):
        rng = np.random.RandomState(0)
        a_np = rng.randn(3, 4).astype(np.float32)
        b_np = rng.randn(4, 5).astype(np.float32)
        a, b = t(a_np, sg=False), t(b_np, sg=False)
        paddle.matmul(a, b).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), b_np.sum(1, keepdims=True).T.repeat(3, 0), rtol=1e-5)

    def test_register_hook(self):
        x = t([1.0], sg=False)
        seen = []
        x.register_hook(lambda g: seen.append(np.asarray(g)) or None)
        (x * 2).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [2.0])

    def test_paddle_grad_api(self):
        x = t([2.0], sg=False)
        y = (x ** 3).sum()
        (g,) = paddle.grad(y, [x])
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-6)
        assert x.grad is None  # paddle.grad must not pollute .grad


class TestPyLayer:
    def test_custom_forward_backward(self):
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                return grad * 2

        x = t([3.0], sg=False)
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), [6.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])


class TestHigherOrder:
    def test_jacobian(self):
        from paddle_tpu.autograd import jacobian

        x = t([1.0, 2.0], sg=False)
        J = jacobian(lambda v: v * v, x)
        np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0]))

    def test_hessian(self):
        from paddle_tpu.autograd import hessian

        x = t([1.0, 2.0], sg=False)
        H = hessian(lambda v: (v ** 3).sum(), x)
        np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0]))


class TestForwardMode:
    """incubate.autograd-style jvp/vjp (reference autograd/functional.py):
    forward-mode is a first-class transform on TPU."""

    def test_jvp_matches_analytic(self):
        import paddle_tpu.autograd as A

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        v = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        out, tangent = A.jvp(lambda t: (t * t).sum(), x, v)
        np.testing.assert_allclose(float(out), 5.0)
        np.testing.assert_allclose(float(tangent), 2.0)  # d/dx0 of sum(x^2) = 2x0

    def test_vjp_matches_backward(self):
        import paddle_tpu.autograd as A

        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        out, g = A.vjp(lambda t: (t ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(g._value), 3 * np.array([1, 4, 9.0]))

    def test_jvp_vjp_consistency(self):
        """<J v, w> == <v, J^T w> on a nonlinear map."""
        import paddle_tpu.autograd as A

        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4).astype(np.float32))
        v = rng.randn(4).astype(np.float32)

        def f(t):
            return paddle.tanh(t * 2.0)

        _, jv = A.jvp(f, x, paddle.to_tensor(v))
        w = rng.randn(4).astype(np.float32)
        _, jtw = A.vjp(f, x, paddle.to_tensor(w))
        lhs = float(np.dot(np.asarray(jv._value), w))
        rhs = float(np.dot(v, np.asarray(jtw._value)))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-5)

    def test_jvp_vjp_multi_output(self):
        import paddle_tpu.autograd as A

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out, tang = A.jvp(lambda t: (t.sum(), (t * t).sum()), x,
                          paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
        np.testing.assert_allclose(float(out[0]), 3.0)
        np.testing.assert_allclose(float(tang[1]), 6.0)  # sum(2x · v)
        out2, g = A.vjp(lambda t: (t.sum(), (t * t).sum()), x,
                        (paddle.to_tensor(np.float32(1.0)),
                         paddle.to_tensor(np.float32(0.5))))
        np.testing.assert_allclose(np.asarray(g._value), [1 + 1.0, 1 + 2.0])

    def test_vjp_list_cotangent_for_tuple_output(self):
        import paddle_tpu.autograd as A

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        # v as a LIST against a tuple-returning func (the documented shape)
        _, g = A.vjp(lambda t: (t.sum(), (t * t).sum()), x,
                     [paddle.to_tensor(np.float32(1.0)),
                      paddle.to_tensor(np.float32(0.5))])
        np.testing.assert_allclose(np.asarray(g._value), [2.0, 3.0])
