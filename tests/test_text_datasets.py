"""Text dataset zoo (reference: python/paddle/text/datasets/)."""
import numpy as np

from paddle_tpu import text
from paddle_tpu.io import DataLoader


def test_imikolov_from_file(tmp_path):
    f = tmp_path / "corpus.txt"
    f.write_text("a b c d e f\n" "a b c d e g\n")
    ds = text.Imikolov(data_file=str(f), window_size=5)
    # 2 windows per 6-token line
    assert len(ds) == 4
    first = ds[0]
    assert first.shape == (5,)
    # vocab built from the file: 7 distinct words
    assert len(ds.word_idx) == 7


def test_ucihousing_file_and_synthetic(tmp_path):
    rows = np.random.RandomState(0).randn(10, 14)
    f = tmp_path / "housing.data"
    np.savetxt(f, rows)
    tr = text.UCIHousing(data_file=str(f), mode="train")
    te = text.UCIHousing(data_file=str(f), mode="test")
    assert len(tr) == 8 and len(te) == 2
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,) and x.dtype == np.float32
    # normalized features
    xs = np.stack([tr[i][0] for i in range(len(tr))])
    assert abs(xs.mean()) < 0.2
    syn = text.UCIHousing()
    assert len(syn) > 0


def test_remaining_datasets_shapes():
    srl = text.Conll05st(samples=4)
    row = srl[0]
    assert len(row) == 7 and all(r.shape == (24,) for r in row)
    ml = text.Movielens(samples=4)
    u = ml[0]
    assert len(u) == 8 and u[5].shape == (3,)
    wmt = text.WMT16(samples=3)
    src, trg_in, trg_next = wmt[0]
    assert trg_in[0] == text.WMT16.BOS and trg_next[-1] == text.WMT16.EOS
    assert len(trg_in) == len(trg_next)
    # integrates with DataLoader
    loader = DataLoader(text.UCIHousing(), batch_size=4, shuffle=False)
    xb, yb = next(iter(loader))
    assert xb.shape[0] == 4 and xb.shape[1] == 13
