"""Model-level numerical parity vs torch (CPU): identical weights + batch
must give matching loss AND gradients through a multi-layer network — the
composite analog of the reference's OpTest, catching interaction bugs that
per-op checks miss (wrong reduction semantics, layer-norm eps placement,
initializer transposes)."""
import numpy as np
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _t(x):
    return torch.tensor(x, requires_grad=True)


class TestMlpClassifierParity:
    def _build(self):
        rng = np.random.RandomState(0)
        w1 = rng.randn(8, 16).astype(np.float32) * 0.3
        b1 = rng.randn(16).astype(np.float32) * 0.1
        g = rng.uniform(0.8, 1.2, 16).astype(np.float32)
        beta = rng.randn(16).astype(np.float32) * 0.05
        w2 = rng.randn(16, 4).astype(np.float32) * 0.3
        b2 = rng.randn(4).astype(np.float32) * 0.1
        x = rng.randn(6, 8).astype(np.float32)
        y = rng.randint(0, 4, (6,)).astype(np.int64)
        return w1, b1, g, beta, w2, b2, x, y

    def test_loss_and_grads_match_torch(self):
        w1, b1, g, beta, w2, b2, x, y = self._build()

        # ---- paddle_tpu ----
        pw = [paddle.to_tensor(a, stop_gradient=False)
              for a in (w1, b1, g, beta, w2, b2)]
        h = F.gelu(F.linear(paddle.to_tensor(x), pw[0], pw[1]))
        h = F.layer_norm(h, [16], weight=pw[2], bias=pw[3])
        logits = F.linear(h, pw[4], pw[5])
        loss = F.cross_entropy(logits, paddle.to_tensor(y))
        loss.backward()
        p_loss = float(loss)
        p_grads = [np.asarray(p.grad._value) for p in pw]

        # ---- torch ----
        tw = [_t(a) for a in (w1, b1, g, beta, w2, b2)]
        th = TF.gelu(torch.tensor(x) @ tw[0] + tw[1])
        th = TF.layer_norm(th, (16,), weight=tw[2], bias=tw[3])
        t_logits = th @ tw[4] + tw[5]
        t_loss = TF.cross_entropy(t_logits, torch.tensor(y))
        t_loss.backward()

        np.testing.assert_allclose(p_loss, float(t_loss), rtol=1e-5)
        for pg, tv, name in zip(p_grads, tw,
                                ("w1", "b1", "gamma", "beta", "w2", "b2")):
            np.testing.assert_allclose(
                pg, tv.grad.numpy(), rtol=1e-4, atol=1e-5,
                err_msg=f"grad mismatch: {name}")

    def test_three_sgd_steps_track_torch(self):
        """Full train-loop parity: losses after 3 SGD steps match."""
        w1, b1, g, beta, w2, b2, x, y = self._build()

        pw = [paddle.to_tensor(a, stop_gradient=False)
              for a in (w1, b1, g, beta, w2, b2)]
        popt = paddle.optimizer.SGD(learning_rate=0.1, parameters=pw)

        tw = [_t(a) for a in (w1, b1, g, beta, w2, b2)]
        topt = torch.optim.SGD(tw, lr=0.1)

        for _ in range(3):
            h = F.gelu(F.linear(paddle.to_tensor(x), pw[0], pw[1]))
            h = F.layer_norm(h, [16], weight=pw[2], bias=pw[3])
            loss = F.cross_entropy(F.linear(h, pw[4], pw[5]),
                                   paddle.to_tensor(y))
            loss.backward()
            popt.step()
            popt.clear_grad()

            th = TF.gelu(torch.tensor(x) @ tw[0] + tw[1])
            th = TF.layer_norm(th, (16,), weight=tw[2], bias=tw[3])
            t_loss = TF.cross_entropy(th @ tw[4] + tw[5], torch.tensor(y))
            topt.zero_grad()
            t_loss.backward()
            topt.step()

            np.testing.assert_allclose(float(loss), float(t_loss), rtol=1e-4)
        for p, t in zip(pw, tw):
            np.testing.assert_allclose(np.asarray(p._value), t.detach().numpy(),
                                       rtol=1e-4, atol=1e-5)


class TestAttentionBlockParity:
    def test_sdpa_block_matches_torch(self):
        """Pre-LN self-attention block: our sdpa + layer_norm + residual vs
        torch's scaled_dot_product_attention composition."""
        rng = np.random.RandomState(1)
        B, S, H, nh = 2, 6, 16, 4
        x = rng.randn(B, S, H).astype(np.float32)
        wq = rng.randn(H, H).astype(np.float32) * 0.2
        wk = rng.randn(H, H).astype(np.float32) * 0.2
        wv = rng.randn(H, H).astype(np.float32) * 0.2
        wo = rng.randn(H, H).astype(np.float32) * 0.2

        pw = [paddle.to_tensor(a, stop_gradient=False) for a in (wq, wk, wv, wo)]
        px = paddle.to_tensor(x)

        def heads_p(t):
            return t.reshape([B, S, nh, H // nh])

        q = heads_p(F.linear(px, pw[0]))
        k = heads_p(F.linear(px, pw[1]))
        v = heads_p(F.linear(px, pw[2]))
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = F.linear(attn.reshape([B, S, H]), pw[3]) + px
        loss = (out * out).mean()
        loss.backward()

        tw = [_t(a) for a in (wq, wk, wv, wo)]
        tx = torch.tensor(x)
        tq = (tx @ tw[0]).view(B, S, nh, H // nh).transpose(1, 2)
        tk = (tx @ tw[1]).view(B, S, nh, H // nh).transpose(1, 2)
        tv = (tx @ tw[2]).view(B, S, nh, H // nh).transpose(1, 2)
        t_attn = TF.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
        t_out = t_attn.transpose(1, 2).reshape(B, S, H) @ tw[3] + tx
        t_loss = (t_out * t_out).mean()
        t_loss.backward()

        np.testing.assert_allclose(float(loss), float(t_loss), rtol=1e-5)
        for pg, tg, name in zip(pw, tw, ("wq", "wk", "wv", "wo")):
            np.testing.assert_allclose(
                np.asarray(pg.grad._value), tg.grad.numpy(),
                rtol=1e-4, atol=1e-5, err_msg=f"grad mismatch {name}")
