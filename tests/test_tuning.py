"""Tier-1 tuning plane: the shared block-size resolver (precedence +
provenance), the JSON tuning cache (round-trip, stale-schema rejection
mirroring the paddle_tpu-npz1 convention), the CPU-interpret autotuner
end-to-end (search -> persist -> load -> dispatch), the persistent AOT
program cache (key safety: geometry/flags/jax-version changes MUST miss;
corrupted entries fall back to a fresh compile with one warning;
round-trips are bit-equal), and the grep guard that keeps all five Pallas
kernels resolving through ONE helper."""
import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.flags import _REGISTRY, flag, set_flags
from paddle_tpu.tuning import (KERNELS, ProgramCache, TuningCache,
                               cache_key, last_resolution, program_counters,
                               resolve_blocks, trial_blocks, tuning_counters)
from paddle_tpu.tuning.blocks import _last

TUNE_FLAGS = ("autotune", "tuning_cache_dir", "program_cache_dir",
              "flash_block_q", "flash_block_k", "flash_bwd_block_q",
              "flash_bwd_block_k", "moe_block_rows", "rmsnorm_block_rows",
              "fused_ce_chunk_tokens", "fused_ce_chunk_vocab",
              "serving_page_size")


@pytest.fixture(autouse=True)
def _flags_hygiene():
    """set_flags marks a flag explicitly-set forever (that IS the override
    signal for real-default flags like serving_page_size), so tests must
    restore the explicit bit along with the value."""
    saved = {n: (_REGISTRY[n].value, _REGISTRY[n].explicit)
             for n in TUNE_FLAGS}
    yield
    for n, (v, ex) in saved.items():
        _REGISTRY[n].value = v
        _REGISTRY[n].explicit = ex
    _last.clear()


def _resolve_rmsnorm(**geom):
    g = {"rows": 512, "d": 128}
    g.update(geom)
    return resolve_blocks("rmsnorm", g, default=lambda _: (256,))


class TestResolvePrecedence:
    def test_default_tier(self):
        res = _resolve_rmsnorm()
        assert res.provenance == "default"
        assert res.values == {"block_rows": 256}
        assert last_resolution("rmsnorm") is res

    def test_flag_override_wins(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        cache.store(cache_key("rmsnorm", {"rows": 512, "d": 128}),
                    {"block_rows": 64})
        set_flags({"rmsnorm_block_rows": 32, "autotune": "load",
                   "tuning_cache_dir": str(tmp_path)})
        res = _resolve_rmsnorm()
        assert res.provenance == "flag"
        assert res.values == {"block_rows": 32}
        assert "FLAGS_rmsnorm_block_rows" in res.source

    def test_tuned_tier_between_flag_and_default(self, tmp_path):
        key = cache_key("rmsnorm", {"rows": 512, "d": 128})
        TuningCache(str(tmp_path)).store(key, {"block_rows": 64})
        set_flags({"autotune": "load", "tuning_cache_dir": str(tmp_path)})
        res = _resolve_rmsnorm()
        assert res.provenance == "tuned"
        assert res.values == {"block_rows": 64}
        assert res.source == key  # provenance names the cache entry

    def test_autotune_off_ignores_cache(self, tmp_path):
        TuningCache(str(tmp_path)).store(
            cache_key("rmsnorm", {"rows": 512, "d": 128}),
            {"block_rows": 64})
        set_flags({"autotune": "off", "tuning_cache_dir": str(tmp_path)})
        assert _resolve_rmsnorm().provenance == "default"

    def test_trial_tier_beats_flags(self):
        set_flags({"rmsnorm_block_rows": 32})
        with trial_blocks("rmsnorm", {"block_rows": 8}):
            res = _resolve_rmsnorm()
            assert res.provenance == "trial"
            assert res.values == {"block_rows": 8}
        assert _resolve_rmsnorm().provenance == "flag"

    def test_partial_override_warns_with_pair_and_provenance(self):
        """The deduplicated flash branch: ONE of the pair set must warn
        naming BOTH flags AND what actually ran, then be ignored."""
        set_flags({"flash_block_q": 256})  # flash_block_k left auto
        with pytest.warns(UserWarning,
                          match="FLAGS_flash_block_q and FLAGS_flash_block_k"
                          ) as rec:
            res = resolve_blocks("flash_fwd", {"seq_len": 1024},
                                 default=lambda g: (512, 1024))
        assert res.provenance == "default"
        assert res.values == {"block_q": 512, "block_k": 1024}
        assert "partial override ignored" in str(rec[0].message)
        assert "default" in str(rec[0].message)  # the fallback provenance

    def test_fused_ce_partial_fills_from_lower_tier(self):
        """fused_ce's historical contract: one chunk flag alone IS a valid
        override; the other parameter fills from the tier below."""
        set_flags({"fused_ce_chunk_tokens": 128})
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no partial-override warning
            res = resolve_blocks("fused_ce",
                                 {"n_tokens": 4096, "vocab": 32000},
                                 default=lambda g: (1024, 32000))
        assert res.provenance == "flag"
        assert res.values == {"chunk_tokens": 128, "chunk_vocab": 32000}
        assert "FLAGS_fused_ce_chunk_tokens" in res.source

    def test_flag_failing_validation_raises(self):
        def validate(values, geometry):
            if geometry["seq_len"] % values["block_q"]:
                raise ValueError("non-divisor")

        set_flags({"flash_block_q": 384, "flash_block_k": 384})
        with pytest.raises(ValueError, match="non-divisor"):
            resolve_blocks("flash_fwd", {"seq_len": 1024},
                           default=lambda g: (512, 1024), validate=validate)

    def test_tuned_failing_validation_degrades(self, tmp_path):
        key = cache_key("rmsnorm", {"rows": 512, "d": 128})
        TuningCache(str(tmp_path)).store(key, {"block_rows": 7})
        set_flags({"autotune": "load", "tuning_cache_dir": str(tmp_path)})

        def validate(values, geometry):
            if values["block_rows"] == 7:
                raise ValueError("bad tuned value")

        with pytest.warns(UserWarning, match="re-tune"):
            res = resolve_blocks("rmsnorm", {"rows": 512, "d": 128},
                                 default=lambda _: (256,),
                                 validate=validate)
        assert res.provenance == "default"

    def test_page_size_explicit_set_detection(self):
        """serving_page_size has a REAL default (16), no 0-sentinel: only
        an explicit set_flags/env set counts as a flag override."""
        res = resolve_blocks("paged_attention",
                             {"num_kv_heads": 4, "head_dim": 64,
                              "max_seq_len": 256},
                             default=lambda g: (16,))
        assert res.provenance == "default"
        set_flags({"serving_page_size": 8})
        res = resolve_blocks("paged_attention",
                             {"num_kv_heads": 4, "head_dim": 64,
                              "max_seq_len": 256},
                             default=lambda g: (16,))
        assert res.provenance == "flag"
        assert res.values == {"page_size": 8}

    def test_resolution_counters_by_provenance(self):
        before = tuning_counters()
        _resolve_rmsnorm()
        set_flags({"rmsnorm_block_rows": 32})
        _resolve_rmsnorm()
        after = tuning_counters()
        assert after["resolutions_default"] == before["resolutions_default"] + 1
        assert after["resolutions_flag"] == before["resolutions_flag"] + 1


class TestTuningCache:
    def test_round_trip(self, tmp_path):
        key = cache_key("rmsnorm", {"rows": 512, "d": 128},
                        platform="cpu")
        cache = TuningCache(str(tmp_path))
        cache.store(key, {"block_rows": 64}, ms=1.25, trials=4)
        re = TuningCache.load(str(tmp_path))
        assert re.lookup(key) == {"block_rows": 64}
        entry = re.entries[key]
        assert entry["ms"] == 1.25 and entry["trials"] == 4
        assert entry["jax"] == jax.__version__

    def test_key_anatomy(self):
        """kernel | sorted geometry | dtype | platform | lowering flags —
        every axis must move the key."""
        base = cache_key("flash_fwd", {"seq_len": 1024}, "bf16", "tpu")
        assert base == ("flash_fwd|seq_len=1024|bf16|tpu|"
                        "flash_segment_block_skip=True")
        assert cache_key("flash_fwd", {"seq_len": 2048}, "bf16", "tpu") != base
        assert cache_key("flash_fwd", {"seq_len": 1024}, "f32", "tpu") != base
        assert cache_key("flash_fwd", {"seq_len": 1024}, "bf16", "cpu") != base
        assert cache_key("flash_bwd", {"seq_len": 1024}, "bf16", "tpu") != base
        set_flags({"flash_segment_block_skip": False})
        try:
            assert cache_key("flash_fwd", {"seq_len": 1024}, "bf16",
                             "tpu") != base
        finally:
            set_flags({"flash_segment_block_skip": True})

    def test_stale_schema_rejected_with_retune_pointer(self, tmp_path):
        """paddle_tpu-npz1 convention: an unknown schema is REJECTED with
        a pointer at the fix, never silently reinterpreted."""
        path = tmp_path / TuningCache.FILENAME
        path.write_text(json.dumps({"format": "paddle_tpu-tune0",
                                    "entries": {"k": {"values": {"b": 1}}}}))
        with pytest.raises(ValueError) as ei:
            TuningCache.load(str(tmp_path))
        msg = str(ei.value)
        assert "paddle_tpu-tune0" in msg and "paddle_tpu-tune1" in msg
        assert "FLAGS_autotune=search" in msg  # the re-tune pointer

    def test_corrupt_json_rejected(self, tmp_path):
        (tmp_path / TuningCache.FILENAME).write_text("{not json")
        with pytest.raises(ValueError, match="re-run the autotuner"):
            TuningCache.load(str(tmp_path))

    def test_resolver_degrades_on_stale_cache(self, tmp_path):
        """Dispatch never crashes on a bad cache file: one warning, one
        reject counter, heuristic blocks."""
        (tmp_path / TuningCache.FILENAME).write_text(
            json.dumps({"format": "paddle_tpu-tune0", "entries": {}}))
        set_flags({"autotune": "load", "tuning_cache_dir": str(tmp_path)})
        before = tuning_counters()["tuning_cache_rejects"]
        with pytest.warns(UserWarning, match="FLAGS_autotune=search"):
            res = _resolve_rmsnorm()
        assert res.provenance == "default"
        assert tuning_counters()["tuning_cache_rejects"] == before + 1
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert _resolve_rmsnorm().provenance == "default"  # warned ONCE


class TestAutotuneEndToEnd:
    def test_search_persist_load_dispatch(self, tmp_path):
        """The acceptance loop on CPU interpret: FLAGS_autotune=search
        times the rmsnorm row-block lattice through the kernel's real
        entry point, persists the winner, and a load-mode resolve consumes
        it with provenance 'tuned'."""
        from paddle_tpu.ops.pallas.rmsnorm_kernel import rmsnorm

        set_flags({"autotune": "search", "tuning_cache_dir": str(tmp_path)})
        trials_before = tuning_counters()["autotune_trials"]
        x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128) / 999.0
        w = jnp.ones((128,), jnp.float32)
        y = rmsnorm(x, w)
        res = last_resolution("rmsnorm")
        assert res is not None and res.provenance == "tuned"
        assert tuning_counters()["autotune_trials"] > trials_before
        # the winner persisted with the current schema
        blob = json.loads((tmp_path / TuningCache.FILENAME).read_text())
        assert blob["format"] == "paddle_tpu-tune1"
        key = cache_key("rmsnorm", {"rows": 64, "d": 128})
        assert blob["entries"][key]["values"] == dict(res.values)
        # numerics match the composite reference
        ref = (x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-6, atol=2e-6)
        # a fresh load-mode process-alike: resolve-only, zero new trials
        _last.clear()
        set_flags({"autotune": "load"})
        trials_before = tuning_counters()["autotune_trials"]
        rmsnorm(x, w)
        res2 = last_resolution("rmsnorm")
        assert res2.provenance == "tuned"
        assert res2.values == res.values
        assert tuning_counters()["autotune_trials"] == trials_before
        # journal carries the search record
        from paddle_tpu.observability import events
        recs = events.journal().recent(component="tuning", n=50)
        assert any(r["event"] == "autotune" for r in recs)

    def test_candidate_lattices_are_legal(self):
        from paddle_tpu.tuning.autotune import (VMEM_BUDGET_BYTES,
                                                candidate_blocks)

        for c in candidate_blocks("flash_fwd", {"seq_len": 2048}):
            assert 2048 % c["block_q"] == 0 and 2048 % c["block_k"] == 0
        for c in candidate_blocks("grouped_matmul",
                                  {"n_rows": 512, "num_groups": 4}):
            assert 512 % c["block_rows"] == 0
        for c in candidate_blocks("fused_ce",
                                  {"n_tokens": 4096, "vocab": 32000}):
            assert c["chunk_tokens"] <= 4096 and c["chunk_vocab"] <= 32000
            assert c["chunk_tokens"] * c["chunk_vocab"] * 4 \
                <= VMEM_BUDGET_BYTES

    def test_metrics_collector_exposes_tuning_counters(self):
        from paddle_tpu.observability import metrics as obs_metrics
        from paddle_tpu.tuning import ensure_metrics_collector

        _resolve_rmsnorm()
        ensure_metrics_collector()
        snap = obs_metrics.registry().snapshot()
        for name in ("compile_cache_hits_total", "compile_cache_misses_total",
                     "autotune_trials_total", "block_resolutions_total",
                     "program_load_ms"):
            assert name in snap, name
        provs = {s["labels"].get("provenance")
                 for s in snap["block_resolutions_total"]["samples"]}
        assert {"flag", "tuned", "default", "trial"} <= provs


def _lower_fn(n=8):
    def f(x):
        return (x * 2.0 + 1.0).sum()

    return jax.jit(f).lower(jnp.ones((n, 4), jnp.float32))


class TestProgramCacheKeys:
    def test_key_sensitivity(self, tmp_path):
        """Geometry, flags fingerprint, jax version, platform tag and the
        caller tag each MUST move the key — drift can only miss, never
        load a stale executable."""
        pc = ProgramCache(str(tmp_path))
        low = _lower_fn(8)
        base = pc.key_for(low, "t")
        assert pc.key_for(low, "t") == base  # deterministic
        assert pc.key_for(_lower_fn(16), "t") != base          # geometry
        assert pc.key_for(low, "t2") != base                   # tag
        assert pc.key_for(low, "t", extra="x") != base         # extra
        assert pc.key_for(low, "t", _jax_version="9.9.9") != base
        assert pc.key_for(low, "t", _flags_fp="{}") != base

    def test_cache_control_flags_do_not_move_the_key(self, tmp_path):
        """FLAGS_autotune/tuning_cache_dir/program_cache_dir select where
        to cache, not what compiles: a warm load-mode process must hit the
        programs a search-mode process persisted."""
        pc = ProgramCache(str(tmp_path))
        low = _lower_fn(8)
        set_flags({"autotune": "search", "tuning_cache_dir": "/x",
                   "program_cache_dir": str(tmp_path)})
        k1 = pc.key_for(low, "t")
        set_flags({"autotune": "load", "tuning_cache_dir": "/y",
                   "program_cache_dir": ""})
        assert pc.key_for(low, "t") == k1
        set_flags({"flash_block_q": 256})  # a REAL flag still moves it
        assert pc.key_for(low, "t") != k1


class TestProgramCacheRoundTrip:
    def test_miss_store_hit_bit_equal(self, tmp_path):
        pc = ProgramCache(str(tmp_path))
        low = _lower_fn(8)
        x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        ex1, s1, ms1 = pc.load_or_compile(low, "rt")
        assert s1 == "miss" and ms1 > 0
        # a second instance over the same dir = a cold process
        ex2, s2, ms2 = ProgramCache(str(tmp_path)).load_or_compile(low, "rt")
        assert s2 == "hit"
        assert float(ex1(x)) == float(ex2(x))  # bit-equal
        assert program_counters()["last_load_ms"] == ms2

    def test_corrupt_entry_falls_back_with_one_warning(self, tmp_path):
        pc = ProgramCache(str(tmp_path))
        low = _lower_fn(8)
        key = pc.key_for(low, "c")
        pc.load_or_compile(low, "c")
        path = os.path.join(str(tmp_path), f"{key}.prog")
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) // 2])  # truncate the payload
        before = program_counters()["corrupt"]
        with pytest.warns(UserWarning, match="unusable program-cache"):
            ex, status, _ = pc.load_or_compile(low, "c")
        assert status == "miss"  # recompiled, never crashed
        assert program_counters()["corrupt"] == before + 1
        x = jnp.ones((8, 4), jnp.float32)
        assert float(ex(x)) == 96.0  # (1*2+1) summed over 8x4
        # the recompile re-stored a good entry; and the warning fired ONCE
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            _, status, _ = pc.load_or_compile(low, "c")
        assert status == "hit"

    def test_alien_header_rejected(self, tmp_path):
        pc = ProgramCache(str(tmp_path))
        low = _lower_fn(8)
        key = pc.key_for(low, "a")
        os.makedirs(str(tmp_path), exist_ok=True)
        with open(os.path.join(str(tmp_path), f"{key}.prog"), "wb") as f:
            f.write(b'{"format": "paddle_tpu-prog0", "payload_bytes": 0}\n')
        before = program_counters()["corrupt"]
        with pytest.warns(UserWarning):
            assert pc.load(key, low) is None
        assert program_counters()["corrupt"] == before + 1


class TestTrainStepAot:
    def test_cold_miss_then_warm_hit_loss_bit_equal(self, tmp_path):
        """CompiledTrainStep through FLAGS_program_cache_dir: the second
        instance (a cold process stand-in) must LOAD and produce the
        bit-identical loss."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             LlamaPretrainingCriterion,
                                             llama_tiny_config)
        from paddle_tpu.parallel import CompiledTrainStep

        set_flags({"program_cache_dir": str(tmp_path)})
        rng = np.random.RandomState(0)
        cfg = llama_tiny_config(num_hidden_layers=1)
        ids = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int64)
        crit = LlamaPretrainingCriterion(cfg)

        def make():
            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            return CompiledTrainStep(m, lambda o, l: crit(o, l),
                                     optimizer=opt)

        s1 = make()
        loss1 = float(s1(ids, ids))
        assert s1.program_cache["status"] == "miss"
        s2 = make()
        loss2 = float(s2(ids, ids))
        assert s2.program_cache["status"] == "hit"
        assert loss1 == loss2
        assert s2.program_cache["ms"] < s1.program_cache["ms"]


@pytest.mark.slow
class TestEngineProgramCache:
    def test_stats_surface_and_warm_load(self, tmp_path):
        """ServingEngine /stats carries the per-program cache outcomes;
        a second engine over the same dir loads every program and streams
        the identical tokens."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        from paddle_tpu.serving import ServingConfig, ServingEngine

        set_flags({"program_cache_dir": str(tmp_path)})
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config())
        m.eval()

        def run():
            eng = ServingEngine(m, ServingConfig(
                page_size=4, num_pages=64, decode_batch=4,
                prefill_chunk=8, max_seq_len=64))
            outs = eng.generate([np.arange(1, 6, dtype=np.int32)],
                                max_new_tokens=4)
            eng.mark_warmup()
            return [int(t) for t in outs[0]], eng.stats()["program_cache"]

        toks1, st1 = run()
        assert st1["enabled"] and st1["dir"] == str(tmp_path)
        assert st1["programs"] and all(
            v["status"] == "miss" for v in st1["programs"].values())
        assert set(st1["at_warmup"]) == set(st1["programs"])
        toks2, st2 = run()
        assert toks2 == toks1
        assert all(v["status"] == "hit" for v in st2["programs"].values())


KERNEL_FILES = {
    "flash_attention.py": ("flash_fwd", "flash_bwd"),
    "grouped_matmul.py": ("grouped_matmul",),
    "fused_ce.py": ("fused_ce",),
    "rmsnorm_kernel.py": ("rmsnorm",),
    "paged_attention.py": ("paged_attention",),
}


class TestSharedResolverGuard:
    """Tier-1 grep guard (ISSUE 20 satellite): every Pallas kernel's block
    pick goes through tuning.blocks.resolve_blocks — a sixth copy of the
    flag/warn pick logic fails here."""

    def _pallas_dir(self):
        import paddle_tpu.ops.pallas as p

        return os.path.dirname(os.path.abspath(p.__file__))

    def test_all_kernels_resolve_through_the_shared_helper(self):
        d = self._pallas_dir()
        for fname, kernels in KERNEL_FILES.items():
            if fname == "paged_attention.py":
                # the page size is resolved ONCE at engine construction
                # (serving/engine.py), not per kernel call
                import paddle_tpu.serving.engine as eng

                src = open(eng.__file__.replace(".pyc", ".py")).read()
            else:
                src = open(os.path.join(d, fname)).read()
            assert "resolve_blocks" in src, (
                f"{fname}: block pick no longer routed through "
                f"tuning.blocks.resolve_blocks")
            for k in kernels:
                assert k in KERNELS

    def test_partial_override_branch_lives_only_in_blocks(self):
        """The deduplicated warn branch must not grow copies again."""
        import paddle_tpu

        root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
        offenders = []
        for dirpath, _, files in os.walk(root):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                if "partial override ignored" in open(path).read():
                    offenders.append(os.path.relpath(path, root))
        assert offenders == [os.path.join("tuning", "blocks.py")], offenders

    def test_kernel_registry_covers_the_contract(self):
        assert set(KERNELS) == {"flash_fwd", "flash_bwd", "grouped_matmul",
                                "fused_ce", "rmsnorm", "paged_attention"}
        for name, spec in KERNELS.items():
            assert len(spec.params) == len(spec.flags) == len(spec.auto)
            for f in spec.flags + spec.lowering_flags:
                flag(f)  # registered
