"""utils.dlpack interop + utils.cpp_extension native custom-op loading
(reference: python/paddle/utils/dlpack.py, utils/cpp_extension/)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension, dlpack


def test_dlpack_torch_roundtrip():
    import torch

    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    p = dlpack.from_dlpack(t)
    assert p.shape == [2, 3]
    np.testing.assert_allclose(np.asarray(p._value), t.numpy())
    back = torch.utils.dlpack.from_dlpack(dlpack.to_dlpack(p * 2))
    np.testing.assert_allclose(back.numpy(), t.numpy() * 2)


def test_cpp_extension_load_and_wrap(tmp_path):
    src = tmp_path / "scale_op.cc"
    src.write_text(
        'extern "C" void scale2(const float* in, float* out, long n) {\n'
        "  for (long i = 0; i < n; ++i) out[i] = 2.0f * in[i];\n"
        "}\n")
    lib = cpp_extension.load("scale_op", [src], build_directory=str(tmp_path),
                             verbose=False)
    import ctypes

    lib.scale2.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_long]

    def scale2(a):
        a = np.ascontiguousarray(a, np.float32)
        out = np.empty_like(a)
        lib.scale2(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   a.size)
        return out

    op = cpp_extension.wrap_host_op(scale2)
    x = paddle.to_tensor(np.arange(5, dtype=np.float32))
    y = op(x)
    np.testing.assert_allclose(np.asarray(y._value), np.arange(5) * 2.0)

    # cache: second load must not rebuild (mtime unchanged)
    mtime = (tmp_path / "scale_op.so").stat().st_mtime
    cpp_extension.load("scale_op", [src], build_directory=str(tmp_path))
    assert (tmp_path / "scale_op.so").stat().st_mtime == mtime
