"""Vision model-family widening (reference python/paddle/vision/models):
VGG, MobileNetV2 (depthwise convs), AlexNet, SqueezeNet — forward shapes
and a real train step each."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import models


@pytest.mark.parametrize("ctor,size", [
    (lambda: models.mobilenet_v2(scale=0.35, num_classes=10), 32),
    (lambda: models.SqueezeNet("1.1", num_classes=10), 64),
])
def test_small_models_train_step(ctor, size):
    paddle.seed(0)
    m = ctor()
    m.train()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, size, size)
                         .astype(np.float32))
    y = paddle.to_tensor(np.array([1, 3], np.int64))
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    out = m(x)
    assert out.shape == [2, 10]
    loss = F.cross_entropy(out, y)
    loss.backward()
    opt.step()
    assert np.isfinite(float(loss))


def test_vgg_structure():
    paddle.seed(0)
    m = models.vgg11(num_classes=7)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(1).randn(1, 3, 224, 224)
                         .astype(np.float32))
    out = m(x)
    assert out.shape == [1, 7]
    # D config has 13 convs; A has 8
    n_convs = sum(1 for _, s in m.named_sublayers()
                  if type(s).__name__ == "Conv2D")
    assert n_convs == 8


def test_alexnet_forward():
    paddle.seed(0)
    m = models.alexnet(num_classes=5)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(2).randn(1, 3, 224, 224)
                         .astype(np.float32))
    assert m(x).shape == [1, 5]


def test_mobilenet_depthwise_residuals():
    m = models.mobilenet_v2(scale=0.35)
    blocks = [s for _, s in m.named_sublayers()
              if isinstance(s, models.InvertedResidual)]
    assert len(blocks) == 17
    assert any(b.use_res for b in blocks)


def test_backbone_mode_and_version_validation():
    import pytest as _pytest

    m = models.mobilenet_v2(scale=0.35, num_classes=0)
    x = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
    feats = m(x)
    assert feats.shape == [1, m.last_channel]
    with _pytest.raises(ValueError):
        models.SqueezeNet(version="2.0")
