"""Vision model families round 2 (reference python/paddle/vision/models):
DenseNet, GoogLeNet, InceptionV3, ShuffleNetV2, MobileNetV1/V3, ResNeXt and
wide-ResNet factories — forward shapes plus a train step on the cheap ones."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import models


def _x(n, size, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(n, 3, size, size).astype(np.float32))


@pytest.mark.parametrize("ctor,size", [
    (lambda: models.shufflenet_v2_x0_25(num_classes=10), 64),
    (lambda: models.mobilenet_v1(scale=0.25, num_classes=10), 64),
    (lambda: models.mobilenet_v3_small(scale=0.5, num_classes=10), 64),
])
def test_small_families_train_step(ctor, size):
    paddle.seed(0)
    m = ctor()
    m.train()
    x = _x(2, size)
    y = paddle.to_tensor(np.array([1, 3], np.int64))
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    out = m(x)
    assert out.shape == [2, 10]
    loss = F.cross_entropy(out, y)
    loss.backward()
    opt.step()
    assert np.isfinite(float(loss))


def test_densenet121_forward_and_growth():
    paddle.seed(0)
    m = models.densenet121(num_classes=6)
    m.eval()
    assert m(_x(1, 64)).shape == [1, 6]
    # 4 dense blocks with 6/12/24/16 layers
    from paddle_tpu.vision.models.densenet import _DenseBlock
    blocks = [s for _, s in m.named_sublayers() if isinstance(s, _DenseBlock)]
    assert [len(b.layers) for b in blocks] == [6, 12, 24, 16]
    assert blocks[-1].out_channels == 1024


def test_googlenet_aux_heads_in_train_mode():
    paddle.seed(0)
    m = models.googlenet(num_classes=4)
    m.train()
    out, aux1, aux2 = m(_x(2, 96))
    assert out.shape == [2, 4] and aux1.shape == [2, 4] and aux2.shape == [2, 4]
    m.eval()
    assert m(_x(2, 96)).shape == [2, 4]


def test_inception_v3_forward():
    paddle.seed(0)
    m = models.inception_v3(num_classes=3)
    m.eval()
    # inception v3 needs >=75px; canonical input is 299
    assert m(_x(1, 128)).shape == [1, 3]


def test_shufflenet_channel_shuffle_permutes():
    from paddle_tpu.vision.models.shufflenetv2 import channel_shuffle
    x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1))
    got = np.asarray(channel_shuffle(x, 2)._value).reshape(-1)
    np.testing.assert_array_equal(got, [0, 4, 1, 5, 2, 6, 3, 7])


def test_resnext_and_wide_factories():
    paddle.seed(0)
    m = models.resnext50_32x4d(num_classes=2)
    # grouped bottleneck: first block conv2 has 32 groups at width 128
    from paddle_tpu.vision.models.resnet import BottleneckBlock
    blk = next(s for _, s in m.named_sublayers() if isinstance(s, BottleneckBlock))
    assert blk.conv2._groups == 32
    w = models.wide_resnet50_2(num_classes=2)
    wblk = next(s for _, s in w.named_sublayers() if isinstance(s, BottleneckBlock))
    # doubled bottleneck width: 64 * (128/64) = 128 channels in stage 1
    assert wblk.conv2.weight.shape[0] == 128
    m.eval()
    assert m(_x(1, 64)).shape == [1, 2]
