"""paddle.vision.ops: nms, roi_align, roi_pool, box_coder
(reference: python/paddle/vision/ops.py; phi roi_align/nms kernels)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


def n32(a):
    return paddle.to_tensor(np.asarray(a, np.int32))


def test_nms_basic_and_per_category():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = np.asarray(vops.nms(t(boxes), 0.5, t(scores))._value)
    np.testing.assert_array_equal(keep, [0, 2])
    # same overlap but different categories: all survive
    cats = paddle.to_tensor(np.array([0, 1, 0], np.int64))
    keep2 = np.asarray(vops.nms(t(boxes), 0.5, t(scores), category_idxs=cats,
                                categories=[0, 1])._value)
    np.testing.assert_array_equal(np.sort(keep2), [0, 1, 2])
    # top_k truncates after scoring order
    keep3 = np.asarray(vops.nms(t(boxes), 0.5, t(scores), top_k=1)._value)
    np.testing.assert_array_equal(keep3, [0])


def test_roi_align_values_and_grad():
    feat = np.ones((1, 2, 8, 8), np.float32)
    rois = np.array([[1., 1., 5., 5.]], np.float32)
    ra = vops.roi_align(t(feat), t(rois), n32([1]), 2)
    assert ra.shape == [1, 2, 2, 2]
    np.testing.assert_allclose(np.asarray(ra._value), 1.0, rtol=1e-5)

    ramp = np.tile(np.arange(8, dtype=np.float32)[None, None, None, :],
                   (1, 1, 8, 1))
    ra2 = vops.roi_align(t(ramp), t(np.array([[2., 2., 6., 6.]], np.float32)),
                         n32([1]), 2, aligned=True)
    v = np.asarray(ra2._value)[0, 0]
    assert v[0, 0] < v[0, 1]          # monotone along the ramp
    assert abs(v[0, 0] - v[1, 0]) < 1e-4  # constant across it

    g = paddle.to_tensor(feat, stop_gradient=False)
    vops.roi_align(g, t(rois), n32([1]), 2).sum().backward()
    assert g.grad is not None and float(np.abs(np.asarray(g.grad._value)).sum()) > 0


def test_roi_align_multi_image_partition():
    feat = np.stack([np.zeros((1, 4, 4), np.float32),
                     np.ones((1, 4, 4), np.float32)])
    rois = np.array([[0., 0., 3., 3.], [0., 0., 3., 3.]], np.float32)
    ra = vops.roi_align(t(feat), t(rois), n32([1, 1]), 1)
    v = np.asarray(ra._value).reshape(2)
    np.testing.assert_allclose(v, [0.0, 1.0], atol=1e-6)


def test_roi_pool_quantized_max():
    ramp = np.tile(np.arange(8, dtype=np.float32)[None, None, None, :],
                   (1, 1, 8, 1))
    rp = vops.roi_pool(t(ramp), t(np.array([[0., 0., 7., 7.]], np.float32)),
                       n32([1]), 2)
    np.testing.assert_allclose(np.asarray(rp._value)[0, 0],
                               [[3., 7.], [3., 7.]])


def test_box_coder_encode_decode_roundtrip():
    priors = np.array([[0., 0., 10., 10.], [5., 5., 15., 15.]], np.float32)
    pvar = np.ones((2, 4), np.float32)
    targets = np.array([[1., 1., 9., 9.]], np.float32)
    enc = vops.box_coder(t(priors), t(pvar), t(targets), "encode_center_size")
    assert enc.shape == [1, 2, 4]
    codes = np.asarray(enc._value)[:, 0, :][None].transpose(1, 0, 2)
    dec = vops.box_coder(t(priors), t(pvar), paddle.to_tensor(codes),
                         "decode_center_size", axis=1)
    np.testing.assert_allclose(np.asarray(dec._value)[0, 0], targets[0],
                               rtol=1e-4, atol=1e-3)
