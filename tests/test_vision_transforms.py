"""Vision transforms functional + class zoo and folder datasets
(reference: python/paddle/vision/transforms, vision/datasets/folder.py)."""
import numpy as np
import pytest

from paddle_tpu.vision import datasets, transforms as T
from paddle_tpu.vision.transforms import functional as F


def _img(h=8, w=8, c=3, seed=0):
    return (np.random.RandomState(seed).rand(h, w, c) * 255).astype(np.uint8)


def test_flips_crops_pads():
    img = _img()
    np.testing.assert_array_equal(F.hflip(img), img[:, ::-1])
    np.testing.assert_array_equal(F.vflip(img), img[::-1])
    assert F.crop(img, 1, 2, 3, 4).shape == (3, 4, 3)
    assert F.center_crop(img, 4).shape == (4, 4, 3)
    padded = F.pad(img, (1, 2), fill=7)
    assert padded.shape == (12, 10, 3) and padded[0, 0, 0] == 7
    refl = F.pad(img, 1, padding_mode="reflect")
    np.testing.assert_array_equal(refl[0, 1], img[1, 0])


def test_resize_short_side_and_exact():
    img = _img(8, 16)
    out = F.resize(img, 4)  # short side -> 4, keep ratio
    assert out.shape == (4, 8, 3)
    assert F.resize(img, (5, 6)).shape == (5, 6, 3)
    # constant image stays constant under bilinear resize
    const = np.full((8, 8, 3), 100, np.uint8)
    np.testing.assert_array_equal(F.resize(const, (4, 4)), 100)


def test_color_adjustments():
    img = _img()
    np.testing.assert_array_equal(F.adjust_brightness(img, 1.0), img)
    dark = F.adjust_brightness(img, 0.5)
    assert dark.mean() < img.mean()
    # contrast 0 collapses to the gray mean
    flat = F.adjust_contrast(img, 0.0)
    assert flat.std() < 2
    # saturation 0 == grayscale
    gray = F.adjust_saturation(img, 0.0)
    assert np.abs(gray[..., 0].astype(int) - gray[..., 1].astype(int)).max() <= 1
    # hue shift of 0 is identity (within rounding)
    same = F.adjust_hue(img, 0.0)
    assert np.abs(same.astype(int) - img.astype(int)).max() <= 1
    g1 = F.to_grayscale(img, 3)
    assert g1.shape == img.shape


def test_rotate_affine_perspective_identity():
    img = _img(9, 9)
    np.testing.assert_array_equal(F.rotate(img, 0.0), img)
    ident = F.affine(img, 0.0, (0, 0), 1.0, 0.0)
    np.testing.assert_array_equal(ident, img)
    # 90-degree rotation is an exact permutation at order 0
    rot = F.rotate(img.astype(np.float32), 90.0)
    np.testing.assert_allclose(rot, np.rot90(img.astype(np.float32)),
                               atol=1e-4)
    pts = [(0, 0), (8, 0), (8, 8), (0, 8)]
    same = F.perspective(img, pts, pts)
    np.testing.assert_array_equal(same, img)


def test_class_transforms_run_and_compose():
    np.random.seed(0)
    img = _img(16, 16)
    pipeline = T.Compose([
        T.RandomResizedCrop(8),
        T.RandomVerticalFlip(0.5),
        T.ColorJitter(0.2, 0.2, 0.2, 0.1),
        T.RandomRotation(10),
        T.RandomErasing(prob=1.0),
        T.Grayscale(3),
    ])
    out = pipeline(img)
    assert out.shape == (8, 8, 3)
    pers = T.RandomPerspective(prob=1.0)(img)
    assert pers.shape == img.shape
    aff = T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.9, 1.1), shear=5)(img)
    assert aff.shape == img.shape


def test_dataset_folder_and_image_folder(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / "root" / cls
        d.mkdir(parents=True)
        for i in range(3):
            np.save(d / f"{i}.npy", np.full((4, 4, 3), i, np.float32))
    ds = datasets.DatasetFolder(str(tmp_path / "root"))
    assert ds.classes == ["cat", "dog"] and len(ds) == 6
    img, label = ds[0]
    assert img.shape == (4, 4, 3) and label in (0, 1)
    flat = tmp_path / "flat"
    flat.mkdir()
    np.save(flat / "a.npy", np.zeros((2, 2), np.float32))
    imf = datasets.ImageFolder(str(flat))
    (only,) = imf[0]
    assert only.shape == (2, 2) and len(imf) == 1


def test_flowers_voc_contracts():
    fl = datasets.Flowers(mode="train", samples=8)
    img, lab = fl[0]
    assert img.shape == (32, 32, 3) and 0 <= int(lab) < 102
    voc = datasets.VOC2012(samples=4, size=32)
    img, mask = voc[0]
    assert img.shape == (32, 32, 3) and mask.shape == (32, 32)
    assert mask.max() < datasets.VOC2012.NUM_CLASSES
