"""ZeRO-3 sharded weights + gather-ahead scan loop (ISSUE 6).

Covers: loss parity of the sharded-weights scan (gather-ahead AND
gather-at-start) against the replicated path, exact parameter-memory
sharding, the HLO CI guard (per-iteration all-gathers in the compiled scan
body, NO up-front full-stack gather), sharded<->replicated state-dict
round-trips with optimizer state and bit-parity resume, per-stage sharding
composition with the pipelined runtimes, the safe npz+JSON deployment
container, and the per-(reason, shape) fallback-warning dedup."""
import re
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.parallel import CompiledTrainStep

ZD = 8  # the virtual device count conftest pins


@pytest.fixture(autouse=True)
def _mesh_teardown():
    yield
    set_mesh(None)


def _model(n_layers=4, **over):
    paddle.seed(0)
    cfg = llama_tiny_config(num_hidden_layers=n_layers, **over)
    return cfg, LlamaForCausalLM(cfg)


def _data(cfg, batch=8, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    return ids, labels


def _step(model, optimizer=None, **kw):
    opt = optimizer or paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters())
    return CompiledTrainStep(model, lambda out, lab: out, optimizer=opt,
                             **kw)


def _run(step, ids, labels, n):
    return [float(step(ids, labels, labels)) for _ in range(n)]


def _per_device_param_bytes(step):
    return sum(v.addressable_shards[0].data.nbytes
               for v in step._param_vals)


def _total_param_bytes(step):
    return sum(int(np.prod(v.shape)) * v.dtype.itemsize
               for v in step._param_vals)


@pytest.fixture(scope="module")
def ref_losses():
    """4 replicated-scan reference losses on the sharding mesh (the zero3
    arms must match these to <=1e-5 rel; in practice bit-identically)."""
    set_mesh(None)
    build_mesh({"sharding": ZD})
    cfg, m = _model(4)
    ids, labels = _data(cfg)
    step = _step(m, scan_layers=True)
    losses = _run(step, ids, labels, 4)
    set_mesh(None)
    return cfg, losses


class TestZero3Parity:
    @pytest.mark.parametrize("mode", ["ahead", "start"])
    def test_losses_match_replicated(self, ref_losses, mode):
        cfg, ref = ref_losses
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        step = _step(m, scan_layers=True, zero_axis="sharding",
                     zero_stage=3, zero3_gather=mode)
        assert step._zero3_scan_info is not None
        assert step._zero3_scan_info.mode == mode
        ids, labels = _data(cfg)
        losses = _run(step, ids, labels, 4)
        np.testing.assert_allclose(losses, ref, rtol=1e-5)
        # params persist reduce-scattered: per-device bytes = total/shard
        assert (_per_device_param_bytes(step)
                <= _total_param_bytes(step) // ZD + 4096)

    def test_dp_sharding_mixed_mesh(self, ref_losses):
        """zero3 over 'sharding' composes with a dp axis (batch sharded over
        both, weights over 'sharding' only)."""
        cfg, _ = ref_losses
        build_mesh({"dp": 2, "sharding": 4})
        _, m_ref = _model(4)
        ids, labels = _data(cfg)
        ref = _run(_step(m_ref, scan_layers=True), ids, labels, 3)
        set_mesh(None)
        build_mesh({"dp": 2, "sharding": 4})
        _, m = _model(4)
        step = _step(m, scan_layers=True, zero_axis="sharding", zero_stage=3)
        losses = _run(step, ids, labels, 3)
        np.testing.assert_allclose(losses, ref, rtol=1e-5)

    def test_mp_sharding_mixed_mesh(self, ref_losses):
        """zero3 composes with tensor parallelism: mp columns keep their mp
        dims (per-column gathers), the rest shard over 'sharding' — and the
        stacked LAYER dim is never chosen for state sharding (it would make
        every scan iteration's state slice cross-device)."""
        cfg, _ = ref_losses
        build_mesh({"sharding": 4, "mp": 2})
        _, m_ref = _model(4)
        ids, labels = _data(cfg)
        ref = _run(_step(m_ref, scan_layers=True), ids, labels, 3)
        set_mesh(None)
        build_mesh({"sharding": 4, "mp": 2})
        _, m = _model(4)
        step = _step(m, scan_layers=True, zero_axis="sharding", zero_stage=3)
        losses = _run(step, ids, labels, 3)
        np.testing.assert_allclose(losses, ref, rtol=1e-5)
        n_outer = len(step._outer_params)
        for st in step._opt_states[n_outer:]:
            for v in st.values():
                spec = getattr(v.sharding, "spec", None)
                if spec and len(spec) > 0:
                    assert spec[0] != "sharding", \
                        "optimizer state sharded on the stacked layer dim"

    def test_interior_remat_policies_rejected(self):
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        with pytest.raises(ValueError, match="sharded stack"):
            _step(m, scan_layers=True, zero_axis="sharding", zero_stage=3,
                  remat="save_dots")

    def test_unknown_gather_mode_rejected(self):
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        with pytest.raises(ValueError, match="zero3 gather mode"):
            _step(m, scan_layers=True, zero_axis="sharding", zero_stage=3,
                  zero3_gather="sometimes")

    def test_typo_axis_warns_instead_of_silent_replicated(self):
        """A zero_axis that names NO mesh axis must not silently train
        replicated at Z x the provisioned parameter memory."""
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        with pytest.warns(UserWarning, match="not a mesh axis"):
            step = _step(m, scan_layers=True, zero_axis="shard",
                         zero_stage=3)
        assert step._zero3_scan_info is None


def _compiled_text(step, ids):
    step._build()
    placed, _ = step._spec_cache.place([ids._value] * 3)
    lowered = step._jitted.lower(
        step._param_vals, step._opt_states, tuple(placed),
        jax.random.key(0), jnp.asarray(1e-3, jnp.float32),
        jnp.asarray(1, jnp.int32))
    return lowered.compile().as_text()


def _all_gather_result_shapes(txt):
    """Leading-dims lists of every all-gather RESULT in optimized HLO."""
    return [
        [int(d) for d in m.group(1).split(",")]
        for m in re.finditer(r"= \w+\[([0-9,]+)\][^=]* all-gather\(", txt)]


class TestHLOGuard:
    """CI guard (tier-1, CPU): the compiled zero3 scan body must gather
    per iteration and must NOT gather the whole parameter stack up front —
    the same inspection style as the PR-2 depth-independence guard."""

    L = 4

    def _text(self, mode):
        build_mesh({"sharding": ZD})
        cfg, m = _model(self.L)
        step = _step(m, scan_layers=True, zero_axis="sharding",
                     zero_stage=3, zero3_gather=mode)
        ids, _ = _data(cfg)
        txt = _compiled_text(step, ids)
        set_mesh(None)
        return txt, step

    def test_gather_ahead_structure(self):
        txt, step = self._text("ahead")
        shapes = _all_gather_result_shapes(txt)
        assert shapes, "no all-gathers in the compiled zero3 step"
        # the stacked decoder columns are never gathered whole: no all-gather
        # result carries the leading layer dim
        n_outer = len(step._outer_params)
        stack_elems = {int(np.prod(v.shape))
                       for v in step._param_vals[n_outer:]}
        for dims in shapes:
            assert dims[0] != self.L or int(np.prod(dims)) not in stack_elems, \
                f"up-front full-stack all-gather found: {dims}"
        # the loop stays a loop (depth-independent program), with the
        # gathers inside it
        assert "while" in txt

    def test_gather_at_start_detected(self):
        """Detector sanity: the overlap-free baseline DOES gather whole
        stacked columns, and the guard's inspection sees it."""
        txt, step = self._text("start")
        shapes = _all_gather_result_shapes(txt)
        n_outer = len(step._outer_params)
        stack_elems = {int(np.prod(v.shape))
                       for v in step._param_vals[n_outer:]}
        assert any(dims[0] == self.L and int(np.prod(dims)) in stack_elems
                   for dims in shapes), \
            "gather-at-start baseline shows no full-stack all-gather"


class TestStateDictRoundTrip:
    """Satellite: save under zero_axis sharding, restore replicated (and
    vice versa), optimizer state included, bit-parity losses after resume."""

    def _checkpoint(self, step, model, optimizer):
        step.sync_params_to_model()
        step.sync_states_to_optimizer()
        sd = {k: np.asarray(v._value) for k, v in model.state_dict().items()}
        return sd, optimizer.state_dict()

    def _restore(self, cfg, sd, opt_sd):
        _, m = _model(4)
        missing, unexpected = m.set_state_dict(sd)
        assert not missing and not unexpected
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        opt.set_state_dict(opt_sd)
        return m, opt

    def test_sharded_to_replicated(self, ref_losses):
        cfg, ref = ref_losses
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = _step(m, optimizer=opt, scan_layers=True,
                     zero_axis="sharding", zero_stage=3)
        ids, labels = _data(cfg)
        first = _run(step, ids, labels, 2)
        sd, opt_sd = self._checkpoint(step, m, opt)
        m2, opt2 = self._restore(cfg, sd, opt_sd)
        step2 = _step(m2, optimizer=opt2, scan_layers=True)  # replicated
        rest = _run(step2, ids, labels, 2)
        np.testing.assert_allclose(first + rest, ref, rtol=1e-5)

    def test_replicated_to_sharded(self, ref_losses):
        cfg, ref = ref_losses
        build_mesh({"sharding": ZD})
        _, m = _model(4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = _step(m, optimizer=opt, scan_layers=True)  # replicated
        ids, labels = _data(cfg)
        first = _run(step, ids, labels, 2)
        sd, opt_sd = self._checkpoint(step, m, opt)
        m2, opt2 = self._restore(cfg, sd, opt_sd)
        step2 = _step(m2, optimizer=opt2, scan_layers=True,
                      zero_axis="sharding", zero_stage=3)
        rest = _run(step2, ids, labels, 2)
        np.testing.assert_allclose(first + rest, ref, rtol=1e-5)

    def test_sharded_resume_bit_parity(self, ref_losses):
        """2 steps sharded -> checkpoint round-trip -> resume sharded must
        continue the uninterrupted 4-step trajectory BIT-exactly."""
        cfg, _ = ref_losses
        build_mesh({"sharding": ZD})
        _, m_a = _model(4)
        opt_a = paddle.optimizer.AdamW(learning_rate=1e-3,
                                       parameters=m_a.parameters())
        step_a = _step(m_a, optimizer=opt_a, scan_layers=True,
                       zero_axis="sharding", zero_stage=3)
        ids, labels = _data(cfg)
        straight = _run(step_a, ids, labels, 4)

        set_mesh(None)
        build_mesh({"sharding": ZD})
        _, m_b = _model(4)
        opt_b = paddle.optimizer.AdamW(learning_rate=1e-3,
                                       parameters=m_b.parameters())
        step_b = _step(m_b, optimizer=opt_b, scan_layers=True,
                       zero_axis="sharding", zero_stage=3)
        first = _run(step_b, ids, labels, 2)
        sd, opt_sd = self._checkpoint(step_b, m_b, opt_b)
        m_c, opt_c = self._restore(cfg, sd, opt_sd)
        step_c = _step(m_c, optimizer=opt_c, scan_layers=True,
                       zero_axis="sharding", zero_stage=3)
        rest = _run(step_c, ids, labels, 2)
        assert first == straight[:2]
        assert rest == straight[2:], (rest, straight[2:])


class TestPipelineZeroAxisGuard:
    def test_zero_axis_must_be_a_data_axis(self):
        """The psum_scatter grad reduction (the all_gather transpose) is
        only correct when the batch is sharded over zero_axis; a non-data
        axis (batch replicated over it) would silently scale dW by the
        shard count — must raise at construction, before any compile."""
        from paddle_tpu.models.llama import (LlamaDecoderLayer,
                                             LlamaPretrainingCriterion,
                                             _EmbeddingStage, _HeadStage)
        from paddle_tpu.parallel.pipeline import PipelinedTrainStep

        cfg = llama_tiny_config(vocab_size=64, hidden_size=32,
                                intermediate_size=64, num_hidden_layers=2,
                                num_attention_heads=2, num_key_value_heads=2,
                                max_position_embeddings=16)
        mesh = build_mesh({"pp": 2, "mp": 2})
        paddle.seed(0)
        embed = _EmbeddingStage(cfg)
        blocks = [LlamaDecoderLayer(cfg) for _ in range(2)]
        head = _HeadStage(cfg)
        crit = LlamaPretrainingCriterion(cfg)
        with pytest.raises(ValueError, match="data axis"):
            PipelinedTrainStep(embed, blocks, head,
                               lambda lg, lb: crit(lg, lb), mesh=mesh,
                               num_micro=2, zero_axis="mp")


@pytest.mark.slow
class TestPipelineComposition:
    """Per-stage sharding composes with pp in both pipelined runtimes."""

    def _modules(self, cfg, n_blocks):
        from paddle_tpu.models.llama import (LlamaDecoderLayer,
                                             LlamaPretrainingCriterion,
                                             _EmbeddingStage, _HeadStage)

        paddle.seed(0)
        embed = _EmbeddingStage(cfg)
        blocks = [LlamaDecoderLayer(cfg) for _ in range(n_blocks)]
        head = _HeadStage(cfg)
        crit = LlamaPretrainingCriterion(cfg)
        params = (embed.parameters()
                  + [p for b in blocks for p in b.parameters()]
                  + head.parameters())
        return embed, blocks, head, crit, params

    def test_1f1b_zero_axis_matches_baseline(self):
        from paddle_tpu.parallel.pipeline import PipelinedTrainStep

        cfg = llama_tiny_config(vocab_size=128, hidden_size=64,
                                intermediate_size=128, num_hidden_layers=4,
                                max_position_embeddings=32)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype(np.int64))
        labels = paddle.to_tensor(
            rng.randint(0, 128, (8, 16)).astype(np.int64))
        losses, per_dev = {}, {}
        for zaxis in (None, "sharding"):
            set_mesh(None)
            mesh = build_mesh({"pp": 2, "dp": 2, "sharding": 2})
            embed, blocks, head, crit, params = self._modules(cfg, 4)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=params)
            step = PipelinedTrainStep(
                embed, blocks, head, lambda lg, lb: crit(lg, lb),
                optimizer=opt, mesh=mesh, num_micro=2, zero_axis=zaxis)
            losses[zaxis] = [float(step(ids, labels)) for _ in range(2)]
            per_dev[zaxis] = sum(v.addressable_shards[0].data.nbytes
                                 for v in step._stacked_blocks)
        np.testing.assert_allclose(losses["sharding"], losses[None],
                                   rtol=1e-5)
        assert per_dev["sharding"] == per_dev[None] // 2

    def test_zbh1_zero_axis_matches_baseline(self):
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
            import _decompose_run
        from paddle_tpu.models.llama import LlamaPretrainingCriterion
        from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 256, (4, 16)).astype(np.int64)
        losses, per_dev = {}, {}
        for zaxis, axes in ((None, {"pp": 2}),
                            ("sharding", {"pp": 2, "sharding": 4})):
            set_mesh(None)
            mesh = build_mesh(axes)
            paddle.seed(0)
            cfg = llama_tiny_config(num_hidden_layers=2,
                                    use_parallel_cross_entropy=False)
            crit = LlamaPretrainingCriterion(cfg)
            pipe = PipelineLayer(
                layers=LlamaForCausalLM.pipeline_layers(cfg), num_stages=2,
                loss_fn=lambda out, lab: crit(out, lab))
            ze, zb, zh = _decompose_run(pipe.run_function, 2)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=pipe.parameters())
            step = ZBH1PipelinedStep(ze, zb, zh, lambda o, l: crit(o, l),
                                     mesh=mesh, num_micro=2, optimizer=opt,
                                     zero_axis=zaxis)
            losses[zaxis] = [float(step(ids, ids)) for _ in range(2)]
            per_dev[zaxis] = sum(v.addressable_shards[0].data.nbytes
                                 for v in step._stacked_blocks)
        np.testing.assert_allclose(losses["sharding"], losses[None],
                                   rtol=1e-5)
        assert per_dev["sharding"] == per_dev[None] // 4


class TestArtifactContainer:
    """Satellite: the .pdmodel container is data-only members + JSON
    metadata; legacy pickle artifacts are rejected with a re-export
    pointer."""

    def test_round_trip_with_bf16(self, tmp_path):
        import ml_dtypes

        from paddle_tpu.inference.artifact import (read_artifact,
                                                   write_artifact)

        path = str(tmp_path / "m.pdmodel")
        params = [np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.ones((2, 2), dtype=ml_dtypes.bfloat16)]
        blob = {"stablehlo": b"\x00mlir-bytes", "params": params,
                "class": "X", "in_shapes": [((1, "b"), "int32")],
                "feed_names": ["x0"], "fetch_count": 2}
        write_artifact(path, blob)
        out = read_artifact(path)
        assert bytes(out["stablehlo"]) == blob["stablehlo"]
        assert out["class"] == "X" and out["fetch_count"] == 2
        for a, b in zip(out["params"], params):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))

    def test_legacy_pickle_rejected_everywhere(self, tmp_path):
        import pickle

        from paddle_tpu.inference.artifact import read_artifact
        from paddle_tpu.inference.serve import Artifact

        path = str(tmp_path / "legacy.pdmodel")
        with open(path, "wb") as f:
            pickle.dump({"stablehlo": b"", "params": []}, f)
        with pytest.raises(ValueError, match="pickle"):
            read_artifact(path)
        with pytest.raises(ValueError, match="jit.save"):
            Artifact(path)

    def test_jit_save_serves_through_container(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import jit
        from paddle_tpu.inference.serve import Artifact
        from paddle_tpu.jit import InputSpec

        paddle.seed(0)
        layer = nn.Linear(4, 3)
        prefix = str(tmp_path / "lin")
        jit.save(layer, prefix,
                 input_spec=[InputSpec([None, 4], "float32")])
        art = Artifact(prefix, warmup=0)
        x = np.ones((2, 4), np.float32)
        got = art.run([x])[0]
        ref = np.asarray(layer(paddle.to_tensor(x))._value)
        np.testing.assert_allclose(got, ref, rtol=1e-6)


class TestFallbackWarningKey:
    """Satellite: the one-time XLA-fallback warning dedups per
    (reason, shape-signature), so a second distinct cause still warns."""

    def test_same_reason_new_shape_warns_again(self):
        import paddle_tpu.nn.functional as Fmod

        Fmod._warned_pallas_blocks.clear()
        try:
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                Fmod._warn_pallas_blocks_once("r1", shape_sig=(1, 48, 2, 8))
                Fmod._warn_pallas_blocks_once("r1", shape_sig=(1, 48, 2, 8))
                Fmod._warn_pallas_blocks_once("r1", shape_sig=(1, 80, 2, 8))
                Fmod._warn_pallas_blocks_once("r2", shape_sig=(1, 48, 2, 8))
            assert len(w) == 3
        finally:
            Fmod._warned_pallas_blocks.clear()
