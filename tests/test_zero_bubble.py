"""Executable ZB-H1 (round-3 verdict item 4): grads parity vs the dense model
and a measured bubble reduction vs the compiled 1F1B runtime.

Reference: distributed/passes/pipeline_scheduler_pass/pipeline_zero_bubble.py.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.parallel.pipeline import PipelinedTrainStep
from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

V, D = 64, 32


class Emb(nn.Layer):
    def __init__(self):
        super().__init__()
        self.e = nn.Embedding(V, D)

    def forward(self, ids):
        return self.e(ids)


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(D, 2 * D)
        self.fc2 = nn.Linear(2 * D, D)

    def forward(self, x):
        return x + self.fc2(paddle.tanh(self.fc1(x)))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.h = nn.Linear(D, V)

    def forward(self, x):
        return self.h(x)


def loss_fn(logits, labels):
    return F.cross_entropy(logits.reshape([-1, V]), labels.reshape([-1]))


@pytest.fixture(autouse=True)
def _clean():
    yield
    set_mesh(None)


def _modules(n_blocks=4, seed=0):
    paddle.seed(seed)
    return Emb(), [Block() for _ in range(n_blocks)], Head()


def _dense_loss_and_grads(embed, blocks, head, ids, labels, M):
    """Reference: plain autodiff over the same modules, mean of per-microbatch
    losses (matching the pipeline's loss convention)."""
    params = (embed.parameters()
              + [p for b in blocks for p in b.parameters()]
              + head.parameters())
    for p in params:
        p.stop_gradient = False
    mbs = ids.shape[0] // M
    total = None
    for m in range(M):
        sl = slice(m * mbs, (m + 1) * mbs)
        x = embed(paddle.to_tensor(ids[sl]))
        for b in blocks:
            x = b(x)
        loss = loss_fn(head(x), paddle.to_tensor(labels[sl]))
        total = loss if total is None else total + loss
    total = total / M
    total.backward()
    return float(total), [np.asarray(p.grad._value) for p in params]


class TestZBH1Parity:
    @pytest.mark.parametrize("S,M,n_blocks", [(4, 4, 4), (4, 6, 8), (2, 4, 4)])
    def test_grads_match_dense(self, S, M, n_blocks):
        embed, blocks, head = _modules(n_blocks)
        rng = np.random.RandomState(0)
        mbs = 2
        ids = rng.randint(0, V, (M * mbs, 8)).astype(np.int64)

        dense_loss, dense_grads = _dense_loss_and_grads(
            embed, blocks, head, ids, ids, M)

        mesh = build_mesh({"pp": S})
        step = ZBH1PipelinedStep(embed, blocks, head, loss_fn, mesh=mesh,
                                 num_micro=M)
        loss, (g_embed, g_stage, g_head) = step.run(ids, ids)
        np.testing.assert_allclose(float(loss), dense_loss, rtol=1e-5)

        n_emb = len(embed.parameters())
        n_per_block = len(blocks[0].parameters())
        # embed grads
        for i in range(n_emb):
            np.testing.assert_allclose(np.asarray(g_embed[i]), dense_grads[i],
                                       rtol=2e-4, atol=1e-5)
        # block grads: g_stage[i] is [S, bps, ...]; dense grads are per-block
        bps = n_blocks // S
        for i in range(n_per_block):
            got = np.asarray(g_stage[i]).reshape(
                (n_blocks,) + np.asarray(g_stage[i]).shape[2:])
            for lb in range(n_blocks):
                want = dense_grads[n_emb + lb * n_per_block + i]
                np.testing.assert_allclose(got[lb], want, rtol=2e-4,
                                           atol=1e-5)
        # head grads
        off = n_emb + n_blocks * n_per_block
        for i in range(len(head.parameters())):
            np.testing.assert_allclose(np.asarray(g_head[i]),
                                       dense_grads[off + i],
                                       rtol=2e-4, atol=1e-5)

    def test_schedule_has_fewer_idle_ticks_than_1f1b_equivalent(self):
        """Table-level accounting: in B/W-split tick units, ZB-H1 idles less
        than 1F1B (whose B tick carries both B and W work = 2 units)."""
        from paddle_tpu.parallel.pipeline_schedules import (
            bubble_fraction, one_f_one_b_schedule, zb_h1_schedule)

        S, M = 4, 8
        zb = zb_h1_schedule(S, M)
        fb = one_f_one_b_schedule(S, M)
        zb_bubble = max(bubble_fraction(zb, r) for r in range(S))
        # 1F1B in split units: each B tick = 2 units of work, T doubles for
        # the B part; idle fraction = 1 - (3M work units) / total units
        fb_ticks = len(fb["ticks"])
        fb_busy = sum(1 for row in fb["ticks"] for c in row if c is not None)
        fb_units = fb_ticks * S + sum(
            1 for row in fb["ticks"] for c in row if c and c[0] == "B")
        fb_bubble_units = 1 - (3 * M * S) / fb_units
        assert zb_bubble < fb_bubble_units + 1e-9


class TestZBH1FleetMode:
    def test_fleet_train_batch_schedule_mode_zbh1(self):
        """strategy.pipeline_configs['schedule_mode']='ZB-H1' routes Fleet
        train_batch through the executable zero-bubble step, end to end with
        the optimizer update."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
        from paddle_tpu.models.llama import (
            LlamaForCausalLM, LlamaPretrainingCriterion, llama_tiny_config)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "sep_degree": 1}
        strategy.pipeline_configs = {"accumulate_steps": 2,
                                     "micro_batch_size": 2,
                                     "schedule_mode": "ZB-H1"}
        fleet.init(is_collective=True, strategy=strategy)

        paddle.seed(0)
        cfg = llama_tiny_config(num_hidden_layers=2,
                                use_parallel_cross_entropy=False)
        crit = LlamaPretrainingCriterion(cfg)
        pipe = PipelineLayer(
            layers=LlamaForCausalLM.pipeline_layers(cfg),
            num_stages=2,
            loss_fn=lambda out, lab: crit(out, lab))
        model = fleet.distributed_model(pipe)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=pipe.parameters()))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 256, (4, 16)).astype(np.int64))
        labels = paddle.to_tensor(
            rng.randint(0, 256, (4, 16)).astype(np.int64))
        l0 = float(model.train_batch([ids, labels], opt))
        l1 = float(model.train_batch([ids, labels], opt))
        l2 = float(model.train_batch([ids, labels], opt))
        from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

        assert isinstance(model._compiled_step, ZBH1PipelinedStep)
        # checkpoint parity: optimizer.state_dict() reflects trained moments
        # after a sync (reference DygraphShardingOptimizer state handling)
        model._sync_from_compiled()
        sd = opt.state_dict()
        assert sd["step"] == 3
        moment_entries = [v for k, v in sd.items() if k.startswith("param_")]
        assert moment_entries, "no optimizer state checkpointed"
        assert any(np.abs(np.asarray(m["m"])).max() > 0
                   for m in moment_entries if "m" in m)
        set_mesh(None)
        assert l2 < l1 < l0


class TestZBH1MeasuredBubble:
    def test_measured_bubble_below_1f1b(self):
        """Wall-clock probe on the virtual 8-device mesh: for each runtime,
        steady per-microbatch cost a = (t(M2)-t(M1))/(M2-M1) and implied
        fill/drain overhead b = t(M1) - M1*a; the bubble fraction b/t(M1)
        must be lower for ZB-H1 (W jobs fill the drain) than for 1F1B."""
        S, M1, M2 = 4, 4, 16
        n_blocks = 4
        mbs = 8
        seq = 16

        def time_step(make_step):
            mesh = build_mesh({"pp": S})
            rng = np.random.RandomState(0)
            out = {}
            for M in (M1, M2):
                # fresh modules per step: PipelinedTrainStep donates + rebinds
                # module params, so instances must not share layers
                embed, blocks, head = _modules(n_blocks)
                step, run = make_step(embed, blocks, head, mesh, M)
                ids = rng.randint(0, V, (M * mbs, seq)).astype(np.int64)
                run(ids)  # compile
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    run(ids)
                    ts.append(time.perf_counter() - t0)
                out[M] = min(ts)
            set_mesh(None)
            return out

        def mk_zb(embed, blocks, head, mesh, M):
            step = ZBH1PipelinedStep(embed, blocks, head, loss_fn, mesh=mesh,
                                     num_micro=M)

            def run(ids):
                loss, _ = step.run(ids, ids)
                return float(loss)

            return step, run

        def mk_fb(embed, blocks, head, mesh, M):
            step = PipelinedTrainStep(embed, blocks, head, loss_fn,
                                      optimizer=None, num_micro=M, remat=True)

            def run(ids):
                return float(step(ids, ids))

            return step, run

        t_zb = time_step(mk_zb)
        t_fb = time_step(mk_fb)

        def bubble(t):
            a = (t[M2] - t[M1]) / (M2 - M1)
            b = t[M1] - M1 * a
            return max(b, 0.0) / t[M1]

        bz, bf = bubble(t_zb), bubble(t_fb)
        # ZB-H1's fill/drain overhead fraction must be measurably lower
        assert bz < bf, (f"zb bubble {bz:.3f} !< 1f1b bubble {bf:.3f} "
                         f"(t_zb={t_zb}, t_fb={t_fb})")


class TestZBH1Debug:
    def test_debug_view_matches_plain(self):
        """debug=True returns per-tick sent activations/cotangents without
        changing the numbers (the instrumentation used to diagnose residual
        routing)."""
        embed, blocks, head = _modules(4)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, V, (4 * 2, 8)).astype(np.int64)
        mesh = build_mesh({"pp": 2})
        plain = ZBH1PipelinedStep(embed, blocks, head, loss_fn, mesh=mesh,
                                  num_micro=2)
        l0, _ = plain.run(ids, ids)
        dbg = ZBH1PipelinedStep(embed, blocks, head, loss_fn, mesh=mesh,
                                num_micro=2, debug=True)
        l1, _ = dbg.run(ids, ids)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        assert dbg._dbg_out and any(k.startswith("y_t") for k in dbg._dbg_out)
        # every debug leaf is stacked over pp (one slice per rank)
        for v in dbg._dbg_out.values():
            assert v.shape[0] == 2
