"""4-process worker: Fleet HybridParallel sub-group collectives across OS
processes (dp=2 x mp=2).

Launched by test_multiprocess.py via `python -m paddle_tpu.distributed.launch
--nproc_per_node 4`. Validates the reference's per-axis ProcessGroup pattern
(fleet/base/topology.py:223-244 creates one comm group per mesh axis;
process_group.h:47 collectives run among MEMBER ranks only):
  1. HybridCommunicateGroup builds dp/mp sub-groups with correct rank lists
  2. eager all_reduce / all_gather / broadcast / reduce over a PROPER
     sub-group, entered only by that group's members, verified vs numpy
  3. peer-addressed send/recv honoring dst/src (not a ring)
  4. sub-group barrier + all_to_all
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}", flush=True)
        sys.exit(1)


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    check(dist.get_world_size() == 4, "world_size != 4")
    check(jax.process_count() == 4, "process_count != 4")

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    # topology (pipe, data, sharding, sep, model) row-major:
    # rank = data*2 + model -> dp groups {0,2},{1,3}? No: data-major means
    # ranks (data, model): 0=(0,0) 1=(0,1) 2=(1,0) 3=(1,1)
    # mp group = fixed data, sweep model -> {0,1} and {2,3}
    # dp group = fixed model, sweep data -> {0,2} and {1,3}
    mp_group = hcg.get_model_parallel_group()
    dp_group = hcg.get_data_parallel_group()
    exp_mp = (0, 1) if rank in (0, 1) else (2, 3)
    exp_dp = (0, 2) if rank in (0, 2) else (1, 3)
    check(tuple(mp_group.ranks) == exp_mp, f"mp ranks {mp_group.ranks} != {exp_mp}")
    check(tuple(dp_group.ranks) == exp_dp, f"dp ranks {dp_group.ranks} != {exp_dp}")
    check(mp_group.rank == exp_mp.index(rank), "mp group-local rank")
    check(dp_group.nranks == 2, "dp group size")

    # ---- sub-group all_reduce: only members enter; sums differ per group ----
    t = paddle.to_tensor(np.full((3,), float(rank + 1), np.float32))
    dist.all_reduce(t, group=mp_group)
    want = float(sum(r + 1 for r in exp_mp))
    np.testing.assert_allclose(t.numpy(), np.full((3,), want, np.float32))

    t2 = paddle.to_tensor(np.full((3,), float(rank + 1), np.float32))
    dist.all_reduce(t2, group=dp_group, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(
        t2.numpy(), np.full((3,), float(max(exp_dp) + 1), np.float32))

    # ---- sub-group all_gather (row order = group rank order) ---------------
    got = []
    dist.all_gather(got, paddle.to_tensor(np.array([rank * 10.0], np.float32)),
                    group=dp_group)
    np.testing.assert_allclose(
        np.concatenate([g.numpy() for g in got]),
        np.array([r * 10.0 for r in exp_dp], np.float32))

    # ---- sub-group broadcast from the group's last member ------------------
    b = paddle.to_tensor(np.full((2,), float(rank), np.float32))
    dist.broadcast(b, src=exp_mp[-1], group=mp_group)
    np.testing.assert_allclose(b.numpy(), np.full((2,), float(exp_mp[-1]), np.float32))

    # ---- reduce to dst: only dst's buffer updated --------------------------
    rt = paddle.to_tensor(np.full((2,), float(rank + 1), np.float32))
    dist.reduce(rt, dst=exp_dp[0], group=dp_group)
    if rank == exp_dp[0]:
        np.testing.assert_allclose(
            rt.numpy(), np.full((2,), float(sum(r + 1 for r in exp_dp)), np.float32))
    else:
        np.testing.assert_allclose(rt.numpy(), np.full((2,), float(rank + 1), np.float32))

    # ---- peer-addressed p2p: 0->3 and 3->0 (neither a ring neighbor pair) --
    if rank == 0:
        dist.send(paddle.to_tensor(np.array([42.0, 43.0], np.float32)), dst=3)
        r = paddle.to_tensor(np.zeros(2, np.float32))
        dist.recv(r, src=3)
        np.testing.assert_allclose(r.numpy(), [7.0, 8.0])
    elif rank == 3:
        r = paddle.to_tensor(np.zeros(2, np.float32))
        dist.recv(r, src=0)
        np.testing.assert_allclose(r.numpy(), [42.0, 43.0])
        dist.send(paddle.to_tensor(np.array([7.0, 8.0], np.float32)), dst=0)

    # ---- sub-group all_to_all over the mp group ----------------------------
    ins = [paddle.to_tensor(np.array([float(rank * 10 + j)], np.float32))
           for j in range(2)]
    outs = []
    dist.all_to_all(outs, ins, group=mp_group)
    pos = exp_mp.index(rank)
    np.testing.assert_allclose(
        np.concatenate([o.numpy() for o in outs]),
        np.array([r * 10.0 + pos for r in exp_mp], np.float32))

    # ---- TensorParallel wrap: mp-REPLICATED params broadcast across the mp
    # group, mp-SHARDED params untouched (reference broadcast_mp_parameters)
    from paddle_tpu.distributed.fleet.meta_parallel import TensorParallel

    class TpToy(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm_w = self.create_parameter([4])   # replicated
            self.shard_w = self.create_parameter([4, 2])
            self.shard_w._mp_pspec = (None, "mp")      # mp-sharded

    paddle.seed(100 + rank)  # different init per rank
    toy = TpToy()
    TensorParallel(toy, hcg)
    from paddle_tpu.distributed import multiproc

    # replicated param now identical across the mp group
    rows = multiproc.subgroup_allgather_np(toy.norm_w.numpy(), exp_mp)
    np.testing.assert_allclose(rows[0], rows[1], rtol=0, atol=0)
    # mp-SHARDED param was NOT overwritten by the mp broadcast: the mp peers
    # still hold different shards (dp broadcast equalizes only across dp)
    srows = multiproc.subgroup_allgather_np(toy.shard_w.numpy(), exp_mp)
    check(not np.allclose(srows[0], srows[1]),
          "mp-sharded param was clobbered by broadcast_mp_parameters")

    # ---- shard_dataloader: DP-dim sharding — mp peers read the SAME rows,
    # dp peers read disjoint halves covering the full batch ------------------
    import paddle_tpu.distributed as pdist

    batches = [np.arange(8, dtype=np.float32).reshape(4, 2)]
    sharded = pdist.shard_dataloader(batches, meshes=None)
    got = np.asarray(list(sharded)[0])
    check(got.shape == (2, 2), f"dp shard shape {got.shape}")
    mp_rows = multiproc.subgroup_allgather_np(got, exp_mp)
    np.testing.assert_allclose(mp_rows[0], mp_rows[1], rtol=0, atol=0)
    dp_rows = multiproc.subgroup_allgather_np(got, exp_dp)
    union = np.sort(dp_rows.reshape(-1, 2), axis=0)
    np.testing.assert_allclose(union, batches[0], rtol=0, atol=0)

    # ---- HybridParallelClipGrad: mp-sharded norms sum over the mp group ----
    from paddle_tpu.distributed.fleet.meta_optimizers.hybrid_parallel_optimizer import (
        HybridParallelClipGrad)
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    p_sh = paddle.to_tensor(np.zeros(2, np.float32))
    p_sh._mp_pspec = ("mp",)
    g_sh = paddle.to_tensor(np.full(2, float(exp_mp.index(rank) + 1), np.float32))
    p_rep = paddle.to_tensor(np.zeros(2, np.float32))
    g_rep = paddle.to_tensor(np.full(2, 2.0, np.float32))
    clip = HybridParallelClipGrad(ClipGradByGlobalNorm(1.0), hcg)
    out_pg = clip([(p_sh, g_sh), (p_rep, g_rep)])
    # true global norm: shard norms over mp (1^2*2 + 2^2*2) + replicated 2^2*2
    true_gn = np.sqrt((1.0 + 4.0) * 2 + 4.0 * 2)
    np.testing.assert_allclose(
        np.asarray(out_pg[1][1]._value), np.full(2, 2.0) / true_gn, rtol=1e-5)

    # ---- sub-group barrier then whole-world barrier ------------------------
    dist.barrier(group=mp_group)
    dist.barrier()
    print(f"rank {rank} HYBRID_WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
