"""2-process worker: real multi-process runtime over jax.distributed.

Launched by test_multiprocess.py via `python -m paddle_tpu.distributed.launch
--nproc_per_node 2`. Validates (reference test pattern:
test/custom_runtime/test_collective_process_group_xccl.py:23-60):
  1. rendezvous: init_parallel_env -> jax.distributed.initialize -> global
     device world spans both processes
  2. eager cross-process collectives (all_reduce/broadcast/all_gather/
     send/recv/object gather) with rank-asymmetric semantics
  3. a jitted computation over a global mesh spanning both processes
  4. eager DDP training with allreduce-averaged grads -> identical losses
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}", flush=True)
        sys.exit(1)


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    check(world == 2, f"world_size {world} != 2")
    check(jax.process_count() == 2, f"process_count {jax.process_count()} != 2")
    check(len(jax.devices()) == 2, f"global devices {len(jax.devices())} != 2")
    check(len(jax.local_devices()) == 1, "expected 1 local device per process")

    # ---- eager cross-process collectives ----------------------------------
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full((4,), 3.0, np.float32))

    b = paddle.to_tensor(np.full((3,), float(rank * 10 + 5), np.float32))
    dist.broadcast(b, src=1)
    np.testing.assert_allclose(b.numpy(), np.full((3,), 15.0, np.float32))

    gathered = []
    dist.all_gather(gathered, paddle.to_tensor(np.array([float(rank)], np.float32)))
    check(len(gathered) == 2, "all_gather length")
    np.testing.assert_allclose(gathered[0].numpy(), [0.0])
    np.testing.assert_allclose(gathered[1].numpy(), [1.0])

    objs = []
    dist.all_gather_object(objs, {"rank": rank, "tag": "x" * (rank + 1)})
    check([o["rank"] for o in objs] == [0, 1], "all_gather_object ranks")

    # rank-asymmetric p2p through the store
    if rank == 0:
        dist.send(paddle.to_tensor(np.arange(6, dtype=np.float32)), dst=1)
    else:
        r = paddle.to_tensor(np.zeros(6, np.float32))
        dist.recv(r, src=0)
        np.testing.assert_allclose(r.numpy(), np.arange(6, dtype=np.float32))

    # partial p2p (reference four_directions_p2p partial_send/recv/allgather):
    # ship only one 1/nranks slice, then reassemble
    full = np.arange(8, dtype=np.float32)
    if rank == 0:
        dist.partial_send(paddle.to_tensor(full), dst=1, nranks=2, rank_id=1)
    else:
        buf = paddle.to_tensor(np.zeros(8, np.float32))
        dist.partial_recv(buf, src=0, nranks=2, rank_id=1)
        np.testing.assert_allclose(buf.numpy()[4:], full[4:])
        np.testing.assert_allclose(buf.numpy()[:4], np.zeros(4))
    pa = paddle.to_tensor(np.where(np.arange(8) // 4 == rank, full, 0.0).astype(np.float32))
    dist.partial_allgather(pa, nranks=2, rank_id=rank)
    np.testing.assert_allclose(pa.numpy(), full)

    # scatter from rank 0
    recv_t = paddle.to_tensor(np.zeros(2, np.float32))
    tl = ([paddle.to_tensor(np.array([1.0, 2.0], np.float32)),
           paddle.to_tensor(np.array([3.0, 4.0], np.float32))] if rank == 0 else None)
    dist.scatter(recv_t, tl, src=0)
    np.testing.assert_allclose(recv_t.numpy(), [1.0, 2.0] if rank == 0 else [3.0, 4.0])

    # ---- jit over the global 2-process mesh -------------------------------
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    local = np.full((2, 4), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (4, 4))
    total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(garr)
    # rank0 shard sums to 8, rank1 to 16
    np.testing.assert_allclose(np.asarray(total), 24.0)

    # ---- eager DDP through the PUBLIC wrapper: param broadcast at wrap +
    # hook-driven allreduce-averaged grads => identical losses ---------------
    from paddle_tpu.distributed import multiproc

    paddle.seed(7 + rank * 31)  # deliberately DIFFERENT init per rank
    model = paddle.DataParallel(paddle.nn.Linear(8, 1))
    # wrap must have broadcast rank0's params to everyone
    w0 = multiproc.broadcast_np(model.weight.numpy(), src=0)
    np.testing.assert_allclose(model.weight.numpy(), w0, rtol=0, atol=0)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(100 + rank)  # different per-rank data
    eval_x = paddle.to_tensor(np.linspace(0, 1, 32, dtype=np.float32).reshape(4, 8))
    eval_y = paddle.to_tensor(np.ones((4, 1), np.float32))
    losses = []
    for _ in range(3):
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 1).astype(np.float32))
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()  # hooks allreduce-average grads; no manual sync
        opt.step()
        opt.clear_grad()
        eval_loss = float(((model(eval_x) - eval_y) ** 2).mean())
        losses.append(eval_loss)

    all_losses = multiproc.exchange_objects(losses)
    np.testing.assert_allclose(all_losses[0], all_losses[1], rtol=0, atol=0)

    # no_sync: local accumulation diverges, the next synced backward reduces
    # the WHOLE accumulated grad (reference EagerReducer/no_sync semantics)
    with model.no_sync():
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 1).astype(np.float32))
        (((model(x) - y) ** 2).mean()).backward()
    g_local = model.weight.grad.numpy().copy()
    g_other = multiproc.allgather_np(g_local)
    check(not np.allclose(g_other[0], g_other[1]),
          "no_sync grads should differ across ranks")
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 1).astype(np.float32))
    (((model(x) - y) ** 2).mean()).backward()
    g_synced = multiproc.allgather_np(model.weight.grad.numpy())
    np.testing.assert_allclose(g_synced[0], g_synced[1], rtol=0, atol=1e-6)
    opt.clear_grad()

    # ---- bucketed reducer (reference EagerReducer reducer.cc:512/:1093):
    # a 100+-param model must issue ceil(total_bytes/buffer) collectives,
    # not one per param, and beat the per-param path's step time ------------
    import time as _time

    class Deep(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.ls = paddle.nn.LayerList(
                [paddle.nn.Linear(16, 16) for _ in range(64)])

        def forward(self, x):
            for l in self.ls:
                x = x + l(x)
            return x

    def run_steps(comm_buffer_size, steps=2):
        paddle.seed(11)
        m = paddle.DataParallel(Deep(), comm_buffer_size=comm_buffer_size)
        o = paddle.optimizer.SGD(learning_rate=0.01,
                                 parameters=m.parameters())
        rngd = np.random.RandomState(5)  # same data: pure comm comparison
        xs = [rngd.randn(4, 16).astype(np.float32) for _ in range(steps)]
        # warm up compile paths before timing
        loss = (m(paddle.to_tensor(xs[0])) ** 2).mean()
        loss.backward(); o.step(); o.clear_grad()
        t0 = _time.perf_counter()
        for i in range(steps):
            loss = (m(paddle.to_tensor(xs[i])) ** 2).mean()
            loss.backward(); o.step(); o.clear_grad()
        return m, _time.perf_counter() - t0

    n_params = len([p for p in Deep().parameters()])
    check(n_params >= 128, f"deep model has {n_params} params, want >= 128")
    # per-param arm FIRST so jax op caches are warm for both timed arms
    # (cold-compile noise otherwise dwarfs the comm-count difference)
    _, t_perparam = run_steps(comm_buffer_size=0)
    mb, t_bucketed = run_steps(comm_buffer_size=25)
    # 64 Linear(16,16) layers: (16*16+16)*4B*128 params ~ 139KB total f32 ->
    # one 1MB first bucket holds everything
    from paddle_tpu.distributed.reducer import assign_buckets

    n_buckets = len(assign_buckets(mb.parameters(), 25, 1))
    check(mb._reducer is not None, "bucketed reducer not installed")
    got = mb._reducer.stats["collectives"]
    want = 3 * n_buckets  # warmup + 2 timed steps
    check(got == want,
          f"bucketed collective count {got} != steps*buckets {want}")
    # grads agree across ranks after a synced backward (rank-dependent data)
    xr = paddle.to_tensor(
        np.random.RandomState(60 + rank).randn(4, 16).astype(np.float32))
    (mb(xr) ** 2).mean().backward()
    gs = multiproc.allgather_np(mb.ls[0].weight.grad.numpy())
    np.testing.assert_allclose(gs[0], gs[1], rtol=0, atol=1e-6)
    check(t_bucketed < t_perparam,
          f"bucketed {t_bucketed:.3f}s not faster than per-param "
          f"{t_perparam:.3f}s over {n_params} params")

    # tied weights: a param used twice per forward must sync its FULL
    # accumulated grad (tape fires the leaf hook once, with the sum)
    class Tied(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.l = paddle.nn.Linear(4, 4)

        def forward(self, x):
            return self.l(self.l(x))

    paddle.seed(9)
    mt = paddle.DataParallel(Tied())
    xt = np.random.RandomState(70 + rank).randn(2, 4).astype(np.float32)
    (mt(paddle.to_tensor(xt)).mean()).backward()
    gt = multiproc.allgather_np(mt.l.weight.grad.numpy())
    np.testing.assert_allclose(gt[0], gt[1], rtol=0, atol=1e-6)
    # and it matches the dense average of per-rank tied-grad computations
    paddle.seed(9)
    ref = Tied()
    for p in ref.parameters():
        p.stop_gradient = False
    (ref(paddle.to_tensor(xt)).mean()).backward()
    both = multiproc.allgather_np(ref.l.weight.grad.numpy())
    np.testing.assert_allclose(gt[0], (both[0] + both[1]) / 2,
                               rtol=1e-5, atol=1e-6)

    # unused-param diagnostics: find_unused_parameters=False raises a guided
    # error instead of deadlocking; =True zero-fills and syncs
    class Branchy(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.used = paddle.nn.Linear(4, 4)
            self.unused = paddle.nn.Linear(4, 4)

        def forward(self, x):
            return self.used(x)

    paddle.seed(3)
    mbad = paddle.DataParallel(Branchy())
    try:
        (mbad(paddle.to_tensor(np.ones((2, 4), np.float32))).mean()).backward()
        check(False, "expected guided unused-param RuntimeError")
    except RuntimeError as e:
        check("find_unused_parameters" in str(e), f"unguided error: {e}")
    paddle.seed(3)
    mok = paddle.DataParallel(Branchy(), find_unused_parameters=True)
    (mok(paddle.to_tensor(np.ones((2, 4), np.float32))).mean()).backward()
    check(mok.unused.weight.grad is not None,
          "unused param grad not zero-synced")
    np.testing.assert_allclose(mok.unused.weight.grad.numpy(),
                               np.zeros((4, 4), np.float32), atol=0)

    # rank-DIVERGENT parameter usage (reducer strict bucket-order posting):
    # rank 0 exercises branch a, rank 1 branch b, with per-param buckets so
    # the buckets COMPLETE in different orders per rank. The next-bucket
    # pointer must still post collectives in identical (index) order, or
    # the ranks would pair mismatched buckets and corrupt every grad.
    class Divergent(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = paddle.nn.Linear(4, 4, bias_attr=False)
            self.b = paddle.nn.Linear(4, 4, bias_attr=False)
            self.c = paddle.nn.Linear(4, 4, bias_attr=False)

        def forward(self, x, branch):
            x = self.c(x)
            return self.a(x) if branch == 0 else self.b(x)

    paddle.seed(21)
    tiny = 32 / (1 << 20)  # 32-byte cap -> one param per bucket
    mdv = paddle.DataParallel(Divergent(), find_unused_parameters=True,
                              comm_buffer_size=tiny,
                              last_comm_buffer_size=tiny)
    from paddle_tpu.distributed.reducer import assign_buckets as _ab

    check(len(_ab(mdv.parameters(), tiny, tiny)) == 3,
          "divergent test needs one bucket per param")
    xdv = np.ones((2, 4), np.float32)
    (mdv(paddle.to_tensor(xdv), rank).mean()).backward()
    for name, p in (("a", mdv.a.weight), ("b", mdv.b.weight),
                    ("c", mdv.c.weight)):
        gs = multiproc.allgather_np(p.grad.numpy())
        np.testing.assert_allclose(gs[0], gs[1], rtol=0, atol=1e-6,
                                   err_msg=f"divergent-usage grad {name}")
    # each branch weight fired on exactly one rank -> synced avg = local/2
    paddle.seed(21)
    ref_dv = Divergent()
    for p in ref_dv.parameters():
        p.stop_gradient = False
    (ref_dv(paddle.to_tensor(xdv), 0).mean()).backward()
    np.testing.assert_allclose(mdv.a.weight.grad.numpy(),
                               ref_dv.a.weight.grad.numpy() / 2,
                               rtol=1e-6, atol=1e-7)

    # collective API tail across real processes: scatter_object_list hands
    # each rank its own object; backend/availability probes agree
    out = []
    dist.scatter_object_list(out, [{"for": 0}, {"for": 1}], src=0)
    check(out == [{"for": rank}], f"scatter_object_list got {out}")
    check(dist.is_available() and dist.get_backend() == "xla", "backend probe")
    dist.monitored_barrier()

    dist.barrier()
    print(f"rank {rank} MP_WORKER_OK losses={losses}", flush=True)


if __name__ == "__main__":
    main()
