"""2-process RPC worker: real remote execution over the TCPStore data plane.

Launched by test_multiprocess.py. Validates (reference test pattern:
test/rpc/test_rpc.py): worker registry, rpc_sync with args/kwargs,
rpc_async futures, remote exception propagation, shutdown barrier.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.distributed.rpc as rpc  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"RPC_WORKER_FAIL: {msg}", flush=True)
        sys.exit(1)


def add(a, b):
    return a + b


def scaled(x, k=2):
    return [v * k for v in x]


def boom():
    raise ValueError("intentional")


def main():
    dist.init_parallel_env()
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    rpc.init_rpc(f"worker{rank}", rank=rank)

    peer = f"worker{1 - rank}"
    info = rpc.get_worker_info(peer)
    check(info.rank == 1 - rank, f"registry: {info}")

    out = rpc.rpc_sync(peer, add, args=(3, 4))
    check(out == 7, f"rpc_sync add -> {out}")

    out = rpc.rpc_sync(peer, scaled, args=([1, 2],), kwargs={"k": 10})
    check(out == [10, 20], f"rpc_sync kwargs -> {out}")

    fut = rpc.rpc_async(peer, add, args=(10, 20))
    check(fut.result(timeout=60) == 30, "rpc_async result")

    try:
        rpc.rpc_sync(peer, boom)
        check(False, "remote exception did not propagate")
    except RuntimeError as e:
        check("intentional" in str(e), f"exception content: {e}")

    rpc.shutdown()
    print("RPC_WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
