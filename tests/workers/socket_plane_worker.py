"""4-process worker: direct-socket eager data plane (round-3 verdict item 7).

Validates correctness (subgroup allgather/allreduce/broadcast/p2p above the
socket threshold match the store-path results) and the performance bar: a
100MB 4-proc allreduce over the socket plane must be well faster than the
TCPStore path.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import multiproc  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}", flush=True)
        sys.exit(1)


def main():
    dist.init_parallel_env()
    rank = jax.process_index()
    world = multiproc.num_processes()
    check(world == 4, f"world {world} != 4")
    ranks = [0, 1, 2, 3][:world]
    sub = [0, 1, 2]  # proper subgroup so the store path is used as baseline

    # -- correctness: socket plane vs small-payload (store) results ----------
    rs = np.random.RandomState(rank)
    big = rs.randn(1 << 19).astype(np.float32)  # 2MB > threshold -> socket
    small = big[:1024].copy()                   # store path

    g_big = multiproc.subgroup_allgather_np(big, ranks)
    g_small = multiproc.subgroup_allgather_np(small, ranks)
    np.testing.assert_allclose(g_big[:, :1024], g_small, rtol=0, atol=0)

    r_big = multiproc.allreduce_np(big[: 1 << 18], op="sum", ranks=sub) \
        if rank in sub else None
    r_small = multiproc.allreduce_np(small, op="sum", ranks=sub) \
        if rank in sub else None
    if rank in sub:
        np.testing.assert_allclose(r_big[:1024], r_small, rtol=1e-6, atol=1e-4)
        # and the value is the true sum
        expect = np.sum([np.random.RandomState(r).randn(1 << 19)[:1024]
                         .astype(np.float32) for r in sub], axis=0)
        np.testing.assert_allclose(r_small, expect, rtol=1e-5, atol=1e-4)

    b = multiproc.subgroup_broadcast_np(
        big if rank == 1 else np.zeros_like(big), src=1, ranks=ranks)
    np.testing.assert_allclose(
        b[:8], np.random.RandomState(1).randn(1 << 19).astype(np.float32)[:8])

    # p2p over the plane
    payload = np.arange(1 << 19, dtype=np.float32) + rank
    if rank == 0:
        multiproc.store_send(payload, dst=3)
    if rank == 3:
        got = multiproc.store_recv(src=0)
        np.testing.assert_allclose(got, np.arange(1 << 19, dtype=np.float32))
    multiproc.barrier()

    # -- the bar: 100MB 4-proc allreduce, socket vs store --------------------
    mb100 = np.full(100 * (1 << 20) // 4, float(rank + 1), np.float32)
    grp = ranks  # 4-member subgroup (not full world): both paths comparable

    def timed(fn):
        multiproc.subgroup_barrier(grp)
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        return out, dt

    # socket ring allreduce
    out_s, t_socket = timed(
        lambda: multiproc.subgroup_allreduce_np(mb100, grp, "sum"))
    # store path, forced via a huge threshold
    old = multiproc._SOCKET_THRESHOLD
    multiproc._SOCKET_THRESHOLD = 1 << 62
    try:
        out_st, t_store = timed(
            lambda: multiproc.subgroup_allreduce_np(mb100, grp, "sum"))
    finally:
        multiproc._SOCKET_THRESHOLD = old
    np.testing.assert_allclose(out_s[:64], out_st[:64], rtol=1e-6)
    np.testing.assert_allclose(out_s[:4], np.full(4, 10.0, np.float32))
    speedup = t_store / t_socket
    print(f"rank {rank} allreduce 100MB: socket {t_socket:.2f}s "
          f"store {t_store:.2f}s speedup {speedup:.1f}x", flush=True)
    speedups = multiproc.exchange_objects(speedup)
    # >2x: on an idle host the measured margin is 50x+, but the full test
    # tier shares one core across 4 workers and the margin compresses — the
    # assert guards the MECHANISM (direct TCP beats store round-trips), not
    # the idle-host constant
    check(max(speedups) > 2.0,
          f"socket plane speedup {max(speedups):.1f}x <= 2x")

    multiproc.barrier()
    print(f"rank {rank} SOCKET_PLANE_OK", flush=True)


if __name__ == "__main__":
    main()
