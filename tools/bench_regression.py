"""Benchmark regression gate.

Reference analog: the reference's benchmark CI (ce framework) that fails a
PR when throughput regresses beyond a tolerance.

Runs bench.py on the current platform and compares tokens/sec (TPU) or just
sanity (CPU smoke: finite loss, flash check skipped) against the recorded
baseline in BENCH_BASELINE.json. Exits nonzero on a >10% regression so the
perf path cannot rot silently. Refresh the baseline intentionally with
`python tools/bench_regression.py --update`.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")
TOLERANCE = 0.10


def run_bench() -> dict:
    res = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=900)
    if res.returncode != 0:
        print(res.stdout[-2000:], res.stderr[-2000:], file=sys.stderr)
        raise SystemExit("bench.py failed")
    for line in reversed(res.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("bench.py produced no JSON line")


def main():
    cur = run_bench()
    platform = cur["detail"]["platform"]
    if "--update" in sys.argv:
        with open(BASELINE, "w") as f:
            json.dump({platform: cur}, f, indent=2)
        print(f"baseline updated for {platform}: {cur['value']} {cur['unit']}")
        return

    if not os.path.exists(BASELINE):
        raise SystemExit(f"no {BASELINE}; record one with --update")
    with open(BASELINE) as f:
        base_all = json.load(f)
    base = base_all.get(platform)
    if base is None:
        print(f"no recorded baseline for platform '{platform}' — run "
              f"--update on this platform first; skipping gate")
        return

    loss = cur["detail"]["loss"]
    if not (loss == loss and abs(loss) < 1e4):
        raise SystemExit(f"bench loss not finite/sane: {loss}")
    ratio = cur["value"] / base["value"]
    print(f"throughput: {cur['value']:.1f} vs baseline {base['value']:.1f} "
          f"({ratio:.3f}x)")
    if platform != "cpu" and not cur["detail"].get("flash_on_hot_path", False):
        raise SystemExit("flash kernel fell off the hot path")
    pipe = cur["detail"].get("pipeline") or {}
    overhead = pipe.get("overhead_vs_theory")
    if overhead is not None:
        # loose gate (the CPU probe is noisy): the schedule must stay within
        # 50% of (M+S-1) tick theory, else the pipeline path rotted
        print(f"pipeline overhead vs theory: {overhead:+.3f}")
        if overhead > 0.5:
            raise SystemExit(
                f"PIPELINE REGRESSION: overhead_vs_theory {overhead:.3f} > 0.5")
    if ratio < 1 - TOLERANCE:
        raise SystemExit(
            f"REGRESSION: {ratio:.3f}x is below the {1 - TOLERANCE:.2f} gate")
    print("bench regression gate passed")


if __name__ == "__main__":
    main()
