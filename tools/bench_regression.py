"""Benchmark regression gate.

Reference analog: the reference's benchmark CI (ce framework) that fails a
PR when throughput regresses beyond a tolerance.

Runs bench.py on the current platform and compares tokens/sec (TPU) or just
sanity (CPU smoke: finite loss, flash check skipped) against the recorded
baseline in BENCH_BASELINE.json. Exits nonzero on a >10% regression so the
perf path cannot rot silently. Refresh the baseline intentionally with
`python tools/bench_regression.py --update`.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")
TOLERANCE = 0.10


def run_bench() -> dict:
    res = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=2100)
    if res.returncode != 0:
        print(res.stdout[-2000:], res.stderr[-2000:], file=sys.stderr)
        raise SystemExit("bench.py failed")
    for line in reversed(res.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("bench.py produced no JSON line")


def _snapshot_value(report: dict, key: str, fallback):
    """Read a gate number from the observability metrics snapshot the
    bench embeds (detail.metrics_snapshot — the registry's own view of
    tokens/sec, MFU and serving p99), falling back to the legacy ad-hoc
    field for reports recorded before the snapshot existed."""
    snap = (report.get("detail") or {}).get("metrics_snapshot") or {}
    v = snap.get(key)
    return float(v) if v is not None else fallback


def _moe_gates(cur: dict):
    """Dropless-MoE self-consistency gates (docs/moe.md): the dropless arm
    must beat the drop-free-sized capacity baseline on the skewed corpus,
    drop nothing, record its kernel block-visit sparsity with the counter
    agreeing with the shared skip predicate, and hold grads parity vs the
    dense-masked reference."""
    moe = (cur["detail"] or {}).get("moe") or {}
    if not moe:
        # fail CLOSED: the arm goes missing exactly when the MoE probe
        # crashed, which is when these gates matter most
        raise SystemExit(
            "MOE REGRESSION: the MOE_JSON arm is missing from the bench "
            "report (probe failed?) — the dropless gates cannot run")
    arms = moe.get("arms") or {}
    d_tps = _snapshot_value(cur, "bench_moe_dropless_tokens_per_sec",
                            (arms.get("dropless") or {})
                            .get("tokens_per_sec"))
    c_tps = _snapshot_value(cur, "bench_moe_capacity_tokens_per_sec",
                            (arms.get("capacity_dropfree") or {})
                            .get("tokens_per_sec"))
    dropped = _snapshot_value(cur, "bench_moe_dropless_dropped_tokens",
                              (arms.get("dropless") or {})
                              .get("dropped_tokens"))
    visits = moe.get("block_visits") or {}
    print(f"moe: dropless {d_tps:.1f} vs drop-free capacity "
          f"{c_tps:.1f} tok/s ({d_tps / c_tps:.2f}x), dropped="
          f"{dropped}, visited_frac={visits.get('visited_frac')}")
    if d_tps < c_tps:
        raise SystemExit(
            f"MOE REGRESSION: dropless {d_tps:.1f} tok/s below the "
            f"capacity baseline {c_tps:.1f}")
    if dropped != 0:
        raise SystemExit(
            f"MOE REGRESSION: dropless arm dropped {dropped} tokens "
            f"(must be 0 by construction)")
    if visits.get("visited_frac") is None:
        raise SystemExit(
            "MOE REGRESSION: block-visit sparsity missing from the "
            "MOE_JSON arm")
    if not visits.get("counts_match_predicate", False):
        raise SystemExit(
            "MOE REGRESSION: grouped-matmul visit-count kernel "
            "disagrees with the shared skip predicate")
    if not (moe.get("grads") or {}).get("parity", False):
        raise SystemExit(
            "MOE REGRESSION: dropless grads diverged from the "
            "dense-masked reference")


def _cache_gates(cur: dict):
    """KV memory-hierarchy self-consistency gates (docs/serving.md): int8
    pages must buy >= 1.9x capacity at a fixed budget and convert it into
    throughput/p99 wins on the budget-matched arms, streams must be
    bit-equal across the host-tier axis and >= 99% token-match across the
    dtype axis, the demote->promote roundtrip (and its promote_fail
    chaos) must reproduce the exact stream, and prefix-affinity placement
    must hold the fleet prefix-hit >= 0.9 where session placement
    scatters it."""
    kv = (cur["detail"] or {}).get("kv_cache") or {}
    if not kv:
        # fail CLOSED: the arm goes missing exactly when the cache probe
        # crashed, which is when these gates matter most
        raise SystemExit(
            "KV-CACHE REGRESSION: the CACHE_JSON arm is missing from the "
            "bench report (probe failed?) — the hierarchy gates cannot run")
    cap = kv["capacity"]
    mat = kv["matrix"]
    tier = kv["tier_roundtrip"]
    routing = kv["routing"]
    arms = mat["arms"]
    print(f"kv-cache: capacity {cap['capacity_ratio']}x, int8 "
          f"{arms['int8_tier']['tokens_per_sec']} vs model "
          f"{arms['model_tier']['tokens_per_sec']} tok/s, int8 match "
          f"{mat['int8_token_match_vs_model']}, fleet prefix-hit "
          f"{routing['prefix']['fleet_prefix_hit']} (session "
          f"{routing['session']['fleet_prefix_hit']})")
    if not cap.get("capacity_ok", False):
        raise SystemExit(
            f"KV-CACHE REGRESSION: int8 capacity ratio "
            f"{cap['capacity_ratio']} below the 1.9x gate")
    if not mat.get("int8_capacity_realized", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: at one byte budget the int8 arm must "
            "serve the burst with ZERO evictions while the model-dtype "
            "arm evicts — the capacity win stopped being realized")
    if not mat.get("int8_overhead_ok", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: int8 arm fell below 0.5x the "
            "model-dtype arm's tokens/sec (dequant overhead blew up)")
    if not mat.get("int8_p99_ok", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: int8 arm p99 above 2x the model-dtype "
            "arm at the same byte budget")
    if not mat.get("model_streams_bit_equal_across_tier", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: host-tier demote/promote changed a "
            "model-dtype greedy stream (roundtrip must be byte-exact)")
    if not mat.get("int8_streams_bit_equal_across_tier", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: host-tier demote/promote changed an "
            "int8 greedy stream (codes+scales roundtrip must be exact)")
    if not mat.get("int8_match_ok", False):
        raise SystemExit(
            f"KV-CACHE REGRESSION: int8 token match "
            f"{mat['int8_token_match_vs_model']} below the 0.99 gate")
    if not mat.get("tier_demotions_exercised", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: the pressured tier arm demoted nothing "
            "— the hierarchy was not exercised")
    if not (mat.get("zero_retrace_ok", False)
            and tier.get("zero_retrace_ok", False)):
        raise SystemExit(
            "KV-CACHE REGRESSION: decode recompiled after warmup on a "
            "hierarchy arm (tier/quant must be shape-stable)")
    if not tier.get("promotions_exercised", False) \
            or not tier.get("stream_equal_after_promote", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: radix hit on a demoted page did not "
            "restore the exact stream via promotion")
    if not (tier.get("chaos") or {}).get("degraded_not_wedged", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: promote_fail chaos did not degrade to "
            "a clean re-prefill of the identical stream")
    if not routing.get("prefix_hit_ok", False):
        raise SystemExit(
            f"KV-CACHE REGRESSION: fleet prefix-hit "
            f"{routing['prefix']['fleet_prefix_hit']} below the 0.9 gate "
            f"under prefix-affinity placement")
    if not routing.get("prefix_beats_session", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: prefix-affinity placement no better "
            "than session placement on the shared-prefix fleet workload")
    if not routing.get("remap_minimal", False):
        raise SystemExit(
            "KV-CACHE REGRESSION: rendezvous remap over prefix keys was "
            "not minimal on membership change")


def _lora_gates(cur: dict):
    """Multi-tenant LoRA self-consistency gates (docs/lora.md): serving
    16 concurrent adapters through one engine must hold >= 0.8x the
    single-adapter tokens/sec on the SAME traffic (the grouped-matmul
    gather is the only difference), p99 must stay within 2x, no arm may
    retrace after warmup, and the swap_fail chaos run must degrade to
    exactly one typed error with every surviving stream completing."""
    lora = (cur["detail"] or {}).get("lora") or {}
    if not lora:
        # fail CLOSED: the arm goes missing exactly when the LoRA probe
        # crashed, which is when these gates matter most
        raise SystemExit(
            "LORA REGRESSION: the LORA_JSON arm is missing from the bench "
            "report (probe failed?) — the multi-tenant gates cannot run")
    arms = lora["arms"]
    print(f"lora: multi16 {arms['multi16']['tokens_per_sec']} vs single "
          f"{arms['single']['tokens_per_sec']} tok/s "
          f"({lora['multi_vs_single_ratio']}x), hot-swap "
          f"{lora['hot_swap']['mean_ms']} ms, artifact "
          f"{lora['adapter_artifact_bytes']} bytes")
    if not lora.get("multi_tenant_ok", False):
        raise SystemExit(
            f"LORA REGRESSION: 16-adapter heterogeneous batching at "
            f"{lora['multi_vs_single_ratio']}x single-tenant tokens/sec "
            f"(gate: >= 0.8x)")
    if not lora.get("p99_ok", False):
        raise SystemExit(
            "LORA REGRESSION: multi-tenant p99 above 2x the "
            "single-tenant p99 on identical traffic")
    if not lora.get("zero_retrace_ok", False):
        raise SystemExit(
            "LORA REGRESSION: decode recompiled after warmup across "
            "adapter mixes (slot ids/pools must be shape-stable)")
    if not (lora.get("chaos") or {}).get("degraded_not_wedged", False):
        raise SystemExit(
            "LORA REGRESSION: swap_fail chaos did not degrade to one "
            "typed error with all surviving streams completing")
    rc = lora.get("router_chaos") or {}
    if not rc.get("ok", False):
        raise SystemExit(
            f"LORA REGRESSION: router chaos with adapters on lost "
            f"{rc.get('lost')} of {rc.get('requests')} streams (failovers="
            f"{rc.get('failovers')}, survivor_zero_retrace="
            f"{rc.get('survivor_zero_retrace')}) — a replica kill must "
            f"fail over adapter traffic with nothing lost")


def _disagg_gates(cur: dict):
    """Disaggregated prefill/decode self-consistency gates
    (docs/serving.md): packed multi-prompt prefill must run >= 1.5x the
    one-at-a-time chunked path with page bytes AND greedy streams
    bit-equal, the split (decode engine + prefill workers) must beat the
    mixed-role engine on decode p99 inter-token gap under the bursty
    workload with a prefill worker killed mid-run, hold goodput within
    5%, lose zero streams (every one bit-equal to the fault-free mixed
    reference — exactly-once under worker death), and neither arm may
    retrace after warmup."""
    dis = (cur["detail"] or {}).get("disagg") or {}
    if not dis:
        # fail CLOSED: the arm goes missing exactly when the disagg probe
        # crashed, which is when these gates matter most
        raise SystemExit(
            "DISAGG REGRESSION: the DISAGG_JSON arm is missing from the "
            "bench report (probe failed?) — the prefill/decode gates "
            "cannot run")
    packed = dis["packed"]
    mixed, split = dis["mixed"], dis["split"]
    retr = dis["retraces"]
    speedup = _snapshot_value(cur, "bench_disagg_packed_speedup",
                              packed["speedup"])
    split_p99 = _snapshot_value(cur, "bench_disagg_split_decode_p99_ms",
                                split["decode_gap_p99_ms"])
    mixed_p99 = _snapshot_value(cur, "bench_disagg_mixed_decode_p99_ms",
                                mixed["decode_gap_p99_ms"])
    print(f"disagg: packed prefill {speedup:.2f}x, decode p99 split "
          f"{split_p99} vs mixed {mixed_p99} ms, goodput "
          f"{split['goodput_tok_s']} vs {mixed['goodput_tok_s']} tok/s, "
          f"kill fired={split['fired']} reclaims={split['reclaims']} "
          f"lost={split['lost']} fill={split['fill']}")
    if speedup < 1.5:
        raise SystemExit(
            f"DISAGG REGRESSION: packed prefill {speedup:.2f}x below the "
            f"1.5x gate over one-at-a-time chunked prefill")
    if not packed.get("pages_equal", False):
        raise SystemExit(
            "DISAGG REGRESSION: packed prefill page bytes diverged from "
            "the sequential reference (must be bit-equal)")
    if not packed.get("streams_equal", False):
        raise SystemExit(
            "DISAGG REGRESSION: packed prefill greedy streams diverged "
            "from the sequential reference (must be bit-equal)")
    if split_p99 is None or mixed_p99 is None or split_p99 > mixed_p99:
        raise SystemExit(
            f"DISAGG REGRESSION: split decode p99 {split_p99} ms must "
            f"beat the mixed-role engine's {mixed_p99} ms on the same "
            f"bursty workload")
    if split["goodput_tok_s"] < 0.95 * mixed["goodput_tok_s"]:
        raise SystemExit(
            f"DISAGG REGRESSION: split goodput {split['goodput_tok_s']} "
            f"below 0.95x the mixed arm's {mixed['goodput_tok_s']} tok/s")
    if split.get("fired") != 1 or split.get("reclaims", 0) < 1:
        raise SystemExit(
            f"DISAGG REGRESSION: the prefill-worker kill did not "
            f"exercise reclaim (fired={split.get('fired')}, "
            f"reclaims={split.get('reclaims')})")
    if split.get("lost", 1) != 0 or mixed.get("lost", 1) != 0:
        raise SystemExit(
            f"DISAGG REGRESSION: lost streams (split={split.get('lost')}, "
            f"mixed={mixed.get('lost')}) — every request must complete")
    if not split.get("streams_equal", False):
        raise SystemExit(
            "DISAGG REGRESSION: split streams under the worker kill "
            "diverged from the fault-free mixed reference (exactly-once "
            "broke)")
    if retr.get("mixed", 1) != 0 or retr.get("split", 1) != 0:
        raise SystemExit(
            f"DISAGG REGRESSION: decode recompiled after warmup "
            f"(mixed={retr.get('mixed')}, split={retr.get('split')})")


def _tuning_gates(cur: dict):
    """AOT program-cache self-consistency gates (docs/autotuning.md): the
    warm pass must LOAD every program the cold pass compiled (train step
    hit, every serving program hit), the warm load must beat the cold
    compile with time-to-ready dropping too, numerics must be bit-equal
    (same loss, same token stream — a hit executes the same compiled
    bytes), and the warm pass must consume the tuned block entry the cold
    pass's autotune search persisted without re-searching."""
    tune = (cur["detail"] or {}).get("tuning_aot") or {}
    if not tune:
        # fail CLOSED: the arm goes missing exactly when the tuning probe
        # crashed, which is when these gates matter most
        raise SystemExit(
            "TUNING REGRESSION: the TUNE_JSON arm is missing from the "
            "bench report (probe failed?) — the AOT cache gates cannot run")
    cold_ms = _snapshot_value(cur, "bench_aot_train_cold_compile_ms",
                              tune["train_cold_compile_ms"])
    warm_ms = _snapshot_value(cur, "bench_aot_train_warm_load_ms",
                              tune["train_warm_load_ms"])
    print(f"tuning/aot: train compile {cold_ms:.0f} -> load {warm_ms:.0f} "
          f"ms ({tune['warm_speedup']}x), ready {tune['ready_cold_ms']} -> "
          f"{tune['ready_warm_ms']} ms, bit_equal loss="
          f"{tune['loss_bit_equal']} tokens={tune['tokens_equal']}, "
          f"trials cold={tune['autotune_trials_cold']}, tuned_consumed="
          f"{tune['tuned_consumed']}")
    if not tune.get("statuses_ok", False):
        raise SystemExit(
            "TUNING REGRESSION: the warm pass did not LOAD every program "
            "the cold pass compiled (hit/miss statuses wrong — cold must "
            "be all miss, warm all hit)")
    if warm_ms >= cold_ms:
        raise SystemExit(
            f"TUNING REGRESSION: warm program load {warm_ms:.0f} ms not "
            f"below the cold compile {cold_ms:.0f} ms — the persistent "
            f"cache stopped paying for itself")
    if tune["ready_warm_ms"] >= tune["ready_cold_ms"]:
        raise SystemExit(
            f"TUNING REGRESSION: warm-cache time-to-ready "
            f"{tune['ready_warm_ms']} ms not below the cold-compile "
            f"{tune['ready_cold_ms']} ms")
    if not (tune.get("loss_bit_equal", False)
            and tune.get("tokens_equal", False)):
        raise SystemExit(
            "TUNING REGRESSION: warm-cache numerics diverged from the "
            "cold compile (loss and token stream must be bit-equal)")
    if tune.get("autotune_trials_cold", 0) < 1:
        raise SystemExit(
            "TUNING REGRESSION: the cold pass timed no autotune "
            "candidates — the search tier did not run")
    if not tune.get("tuned_consumed", False):
        raise SystemExit(
            "TUNING REGRESSION: the warm pass did not consume the tuned "
            "block entry the cold search persisted (provenance must be "
            "'tuned' with zero new trials)")


def main():
    cur = run_bench()
    platform = cur["detail"]["platform"]
    if "--update" in sys.argv:
        with open(BASELINE, "w") as f:
            json.dump({platform: cur}, f, indent=2)
        print(f"baseline updated for {platform}: {cur['value']} {cur['unit']}")
        return

    # self-consistency gates first: they compare arms WITHIN this run, so
    # they hold on any platform, baseline recorded or not
    _moe_gates(cur)
    _cache_gates(cur)
    _lora_gates(cur)
    _disagg_gates(cur)
    _tuning_gates(cur)

    if not os.path.exists(BASELINE):
        raise SystemExit(f"no {BASELINE}; record one with --update")
    with open(BASELINE) as f:
        base_all = json.load(f)
    base = base_all.get(platform)
    if base is None:
        print(f"no recorded baseline for platform '{platform}' — run "
              f"--update on this platform first; skipping baseline gate")
        return

    loss = cur["detail"]["loss"]
    if not (loss == loss and abs(loss) < 1e4):
        raise SystemExit(f"bench loss not finite/sane: {loss}")
    # primary numbers come from the metrics snapshot (the observability
    # plane IS the instrument); legacy fields remain the fallback so old
    # baselines stay comparable
    cur_tps = _snapshot_value(cur, "bench_tokens_per_sec_per_chip",
                              cur["value"])
    base_tps = _snapshot_value(base, "bench_tokens_per_sec_per_chip",
                               base["value"])
    ratio = cur_tps / base_tps
    print(f"throughput: {cur_tps:.1f} vs baseline {base_tps:.1f} "
          f"({ratio:.3f}x)")
    mfu = _snapshot_value(cur, "bench_mfu",
                          (cur["detail"] or {}).get("mfu"))
    if mfu is not None:
        print(f"mfu: {mfu:.4f} "
              f"(source: {(cur['detail'].get('metrics_snapshot') or {}) .get('mfu_source', 'analytic')})")
    p99 = _snapshot_value(cur, "bench_serving_p99_ms", None)
    base_p99 = _snapshot_value(base, "bench_serving_p99_ms", None)
    if p99 is not None:
        print(f"serving p99: {p99:.1f} ms"
              + (f" vs baseline {base_p99:.1f} ms" if base_p99 else ""))
        if base_p99 and p99 > base_p99 * 2.0:
            raise SystemExit(
                f"SERVING REGRESSION: p99 {p99:.1f} ms is more than 2x "
                f"the recorded {base_p99:.1f} ms baseline")
    if platform != "cpu" and not cur["detail"].get("flash_on_hot_path", False):
        raise SystemExit("flash kernel fell off the hot path")
    pipe = cur["detail"].get("pipeline") or {}
    overhead = pipe.get("overhead_vs_theory")
    if overhead is not None:
        # loose gate (the CPU probe is noisy): the schedule must stay within
        # 50% of (M+S-1) tick theory, else the pipeline path rotted
        print(f"pipeline overhead vs theory: {overhead:+.3f}")
        if overhead > 0.5:
            raise SystemExit(
                f"PIPELINE REGRESSION: overhead_vs_theory {overhead:.3f} > 0.5")
    if ratio < 1 - TOLERANCE:
        raise SystemExit(
            f"REGRESSION: {ratio:.3f}x is below the {1 - TOLERANCE:.2f} gate")
    obs = (cur["detail"] or {}).get("observability") or {}
    tr, sv = obs.get("train") or {}, obs.get("serving") or {}
    if tr or sv:
        print(f"observability overhead: train "
              f"{tr.get('overhead_frac')} serving {sv.get('overhead_frac')} "
              f"(gates <2%: {tr.get('overhead_lt_2pct')}/"
              f"{sv.get('overhead_lt_2pct')}); losses_bit_equal="
              f"{tr.get('losses_bit_equal')} retraces="
              f"{sv.get('decode_retraces_after_warmup')}")
        if tr.get("losses_bit_equal") is False:
            raise SystemExit(
                "OBSERVABILITY REGRESSION: step telemetry changed the "
                "training losses")
        if sv.get("decode_retraces_after_warmup"):
            raise SystemExit(
                "OBSERVABILITY REGRESSION: instrumented decode recompiled "
                "after warmup")
    print("bench regression gate passed")


if __name__ == "__main__":
    main()
