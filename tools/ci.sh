#!/usr/bin/env bash
# CI entry point (reference analog: the reference repo's CI pipelines under
# tools/ + paddle_build.sh test stages, with testslist.csv-style run tiers).
#
# Usage:
#   tools/ci.sh quick     per-commit tier: import hygiene + fast unit subset
#                         (-m "not slow"), <3 min on the CI host
#   tools/ci.sh           full gate: everything below
#   tools/ci.sh nightly   full gate + 200-step loss-curve parity vs torch
#
# Stages (full):
#   1. import hygiene: importing paddle_tpu must NOT initialize the XLA
#      backend (jax.distributed would break)
#   1c. tuning plane: block-size resolver precedence/provenance, the JSON
#      tuning cache, and the persistent AOT program cache (key safety,
#      corrupt-entry fallback, warm-load bit-equality)
#   2. unit suite on the virtual 8-device CPU mesh
#   3. driver multichip gate: 8-device dryrun of the full sharded train step
#   4. bench smoke (CPU config) + regression check against the recorded
#      baseline (tools/bench_regression.py), incl. the warm-vs-cold
#      TUNE_JSON gates
set -euo pipefail
cd "$(dirname "$0")/.."

TIER="${1:-full}"

echo "== [1] import hygiene =="
python - <<'EOF'
import jax, paddle_tpu
from jax._src import xla_bridge
assert not xla_bridge._backends, "import paddle_tpu initialized the XLA backend"
print("ok: lazy backend")
EOF

echo "== [1b] observability plane (not slow) =="
# the instrument every other gate reads from is verified FIRST: metrics
# registry exposition, trace-id propagation, step telemetry, event journal
python -m pytest tests/test_observability.py -q -m "not slow"

echo "== [1c] tuning plane (not slow) =="
# the autotuner + AOT program cache feed every compile the later stages
# time: resolver precedence, cache-key safety and corrupt-entry fallback
# are verified before any stage that could silently eat a stale program
python -m pytest tests/test_tuning.py -q -m "not slow"

if [ "$TIER" = "quick" ]; then
  echo "== [2] unit suite (quick tier) =="
  # [1b]/[1c] already ran the observability + tuning modules; don't pay
  # their XLA compiles twice per CI run
  python -m pytest tests/ -q -m "not slow" --ignore=tests/test_observability.py --ignore=tests/test_tuning.py
  echo "CI QUICK TIER PASSED"
  exit 0
fi

echo "== [2] unit suite (full) =="
python -m pytest tests/ -q --ignore=tests/test_observability.py --ignore=tests/test_tuning.py

echo "== [3] multichip gate =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== [4] bench regression =="
python tools/bench_regression.py

if [ "$TIER" = "nightly" ]; then
  echo "== [5] loss-curve parity (200 steps, fp32 + bf16, vs torch) =="
  PARITY_STEPS=200 PARITY_BF16=1 python -m pytest tests/test_loss_parity.py -q
  echo "== [6] parallel-mode loss parity (200 steps, dp/mp/pp/zero2) =="
  PARALLEL_PARITY_STEPS=200 python -m pytest tests/test_parallel_parity.py -q
fi

echo "CI PASSED"
