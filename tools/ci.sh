#!/usr/bin/env bash
# CI entry point (reference analog: the reference repo's CI pipelines under
# tools/ + paddle_build.sh test stages). Stages:
#   1. import hygiene: importing paddle_tpu must NOT initialize the XLA
#      backend (jax.distributed would break)
#   2. unit suite on the virtual 8-device CPU mesh
#   3. driver multichip gate: 8-device dryrun of the full sharded train step
#   4. bench smoke (CPU config) + regression check against the recorded
#      baseline (tools/bench_regression.py)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/4] import hygiene =="
python - <<'EOF'
import jax, paddle_tpu
from jax._src import xla_bridge
assert not xla_bridge._backends, "import paddle_tpu initialized the XLA backend"
print("ok: lazy backend")
EOF

echo "== [2/4] unit suite =="
python -m pytest tests/ -q

echo "== [3/4] multichip gate =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== [4/4] bench regression =="
python tools/bench_regression.py

echo "CI PASSED"
