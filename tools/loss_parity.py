"""Loss-curve parity: paddle_tpu vs an INDEPENDENT torch implementation.

The BASELINE.md metric is "loss-curve parity vs the GPU reference run". This
harness trains the same ~8M-param LLaMA config for N steps in paddle_tpu and
in a from-scratch torch twin (written against the LLaMA paper, not against
paddle_tpu's code): identical init (params exported once and loaded into
torch), identical data stream, identical AdamW hyperparameters. It returns
both loss curves; the test asserts the max per-step deviation.

Canary: `perturb="beta2"` deliberately mis-sets the torch AdamW beta2 — the
assertion must catch it (same philosophy as the numeric harness's planted
wrong-vjp).

Run standalone:  python tools/loss_parity.py [steps] > curves.json
"""
from __future__ import annotations

import math
import sys

import numpy as np

CFG = dict(vocab=4096, hidden=256, inter=688, layers=8, heads=4, seq=128,
           batch=8, lr=3e-4, wd=0.01, betas=(0.9, 0.999), eps=1e-8, pool=8)


def _data_pool(cfg=CFG, seed=1234):
    """Fixed pool of batches, cycled — memorization drives the curve down."""
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg["vocab"], (cfg["batch"], cfg["seq"]))
            .astype(np.int64) for _ in range(cfg["pool"])]


# --------------------------------------------------------------------------
# paddle_tpu side


def run_paddle(steps: int, cfg=CFG, dtype="float32"):
    """Returns (losses, init_state_dict as numpy)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    lcfg = LlamaConfig(
        vocab_size=cfg["vocab"], hidden_size=cfg["hidden"],
        intermediate_size=cfg["inter"], num_hidden_layers=cfg["layers"],
        num_attention_heads=cfg["heads"], num_key_value_heads=cfg["heads"],
        max_position_embeddings=cfg["seq"], use_parallel_cross_entropy=False)
    paddle.seed(0)
    model = LlamaForCausalLM(lcfg)
    init = {k: np.asarray(v._value, np.float32).copy()
            for k, v in model.state_dict().items()}
    if dtype == "bfloat16":
        model.to(dtype="bfloat16")
    model.train()
    opt = paddle.optimizer.AdamW(
        learning_rate=cfg["lr"], beta1=cfg["betas"][0], beta2=cfg["betas"][1],
        epsilon=cfg["eps"], weight_decay=cfg["wd"],
        parameters=model.parameters(),
        multi_precision=(dtype == "bfloat16"))
    pool = _data_pool(cfg)
    losses = []
    for i in range(steps):
        ids = paddle.to_tensor(pool[i % len(pool)])
        loss = model(ids, ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses, init


# --------------------------------------------------------------------------
# independent torch twin (from the LLaMA paper: RMSNorm, RoPE, SwiGLU,
# causal attention, untied head, CE over all positions)


def _torch_model(cfg, init):
    import torch
    import torch.nn as tn

    h, heads = cfg["hidden"], cfg["heads"]
    hd = h // heads

    class RMSNorm(tn.Module):
        def __init__(self, n, eps=1e-5):
            super().__init__()
            self.w = tn.Parameter(torch.ones(n))
            self.eps = eps

        def forward(self, x):
            var = x.float().pow(2).mean(-1, keepdim=True)
            return (x.float() * torch.rsqrt(var + self.eps)).to(x.dtype) * self.w

    class Block(tn.Module):
        def __init__(self):
            super().__init__()
            self.ln1 = RMSNorm(h)
            self.ln2 = RMSNorm(h)
            self.q = tn.Linear(h, h, bias=False)
            self.k = tn.Linear(h, h, bias=False)
            self.v = tn.Linear(h, h, bias=False)
            self.o = tn.Linear(h, h, bias=False)
            self.gate = tn.Linear(h, cfg["inter"], bias=False)
            self.up = tn.Linear(h, cfg["inter"], bias=False)
            self.down = tn.Linear(cfg["inter"], h, bias=False)

        def attn(self, x, cos, sin):
            b, s, _ = x.shape
            q = self.q(x).view(b, s, heads, hd)
            k = self.k(x).view(b, s, heads, hd)
            v = self.v(x).view(b, s, heads, hd)

            def rope(t):
                t1, t2 = t.chunk(2, dim=-1)
                c = cos[None, :s, None, :]
                sn = sin[None, :s, None, :]
                return torch.cat([t1 * c - t2 * sn, t2 * c + t1 * sn], -1)

            q, k = rope(q), rope(k)
            q, k, v = (t.transpose(1, 2) for t in (q, k, v))  # [B,H,S,D]
            att = (q @ k.transpose(-2, -1)) / math.sqrt(hd)
            mask = torch.full((s, s), float("-inf")).triu(1)
            att = torch.softmax(att + mask, dim=-1)
            out = (att @ v).transpose(1, 2).reshape(b, s, h)
            return self.o(out)

        def forward(self, x, cos, sin):
            x = x + self.attn(self.ln1(x), cos, sin)
            x = x + self.down(torch.nn.functional.silu(self.gate(self.ln2(x)))
                              * self.up(self.ln2(x)))
            return x

    class Model(tn.Module):
        def __init__(self):
            super().__init__()
            self.emb = tn.Embedding(cfg["vocab"], h)
            self.blocks = tn.ModuleList([Block() for _ in range(cfg["layers"])])
            self.norm = RMSNorm(h)
            self.head = tn.Linear(h, cfg["vocab"], bias=False)
            inv = 1.0 / (10000.0 ** (torch.arange(0, hd, 2).float() / hd))
            t = torch.arange(cfg["seq"]).float()
            freqs = torch.outer(t, inv)
            self.register_buffer("cos", freqs.cos())
            self.register_buffer("sin", freqs.sin())

        def forward(self, ids):
            x = self.emb(ids)
            for blk in self.blocks:
                x = blk(x, self.cos, self.sin)
            return self.head(self.norm(x))

    m = Model()

    def cp(dst, src_key, transpose=False):
        w = torch.tensor(init[src_key])
        dst.data.copy_(w.t() if transpose else w)

    cp(m.emb.weight, "llama.embed_tokens.weight")
    cp(m.head.weight, "lm_head.weight", transpose=True)
    cp(m.norm.w, "llama.norm.weight")
    for i, blk in enumerate(m.blocks):
        pre = f"llama.layers.{i}."
        cp(blk.ln1.w, pre + "input_layernorm.weight")
        cp(blk.ln2.w, pre + "post_attention_layernorm.weight")
        cp(blk.q.weight, pre + "self_attn.q_proj.weight", transpose=True)
        cp(blk.k.weight, pre + "self_attn.k_proj.weight", transpose=True)
        cp(blk.v.weight, pre + "self_attn.v_proj.weight", transpose=True)
        cp(blk.o.weight, pre + "self_attn.o_proj.weight", transpose=True)
        cp(blk.gate.weight, pre + "mlp.gate_proj.weight", transpose=True)
        cp(blk.up.weight, pre + "mlp.up_proj.weight", transpose=True)
        cp(blk.down.weight, pre + "mlp.down_proj.weight", transpose=True)
    return m


def run_torch(steps: int, init, cfg=CFG, perturb=None):
    import torch

    torch.manual_seed(0)
    m = _torch_model(cfg, init)
    betas = cfg["betas"]
    if perturb == "beta2":  # canary: deliberately wrong optimizer
        betas = (betas[0], 0.95)
    opt = torch.optim.AdamW(m.parameters(), lr=cfg["lr"], betas=betas,
                            eps=cfg["eps"], weight_decay=cfg["wd"])
    pool = _data_pool(cfg)
    losses = []
    for i in range(steps):
        ids = torch.tensor(pool[i % len(pool)])
        logits = m(ids)
        loss = torch.nn.functional.cross_entropy(
            logits.reshape(-1, cfg["vocab"]), ids.reshape(-1))
        opt.zero_grad()
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return losses


def run_parity(steps: int = 200, dtype: str = "float32", perturb=None):
    """Returns (paddle_losses, torch_losses, max_abs_dev)."""
    pl, init = run_paddle(steps, dtype=dtype)
    tl = run_torch(steps, init, perturb=perturb)
    dev = float(np.max(np.abs(np.asarray(pl) - np.asarray(tl))))
    return pl, tl, dev


if __name__ == "__main__":
    import json

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    out = {}
    for dtype in ("float32", "bfloat16"):
        pl, tl, dev = run_parity(steps, dtype=dtype)
        out[dtype] = {"paddle_tpu": pl, "torch": tl,
                      "max_abs_dev": round(dev, 6)}
        print(f"{dtype}: max |dev| over {steps} steps = {dev:.5f}",
              file=sys.stderr)
    print(json.dumps(out))
