"""200-step loss parity under parallelism (round-5 verdict item 4).

Trains the SAME small LLaMA (identical init via seed, identical data pool,
identical AdamW) on the virtual 8-CPU mesh under each parallel mode and
asserts the loss curve matches the single-device fp32 curve:

  single   : {dp:1} CompiledTrainStep
  dp2      : {dp:2} GSPMD data parallelism
  mp2      : {mp:2} Megatron TP (mpu Column/RowParallel + VocabParallel)
  zero2    : {dp:2} + zero_axis='dp' optimizer-state sharding
  pp2_1f1b : {pp:2} compiled 1F1B, 2 microbatches
  pp2_zbh1 : {pp:2} executable ZB-H1, 2 microbatches

This is the strongest multi-chip correctness proof a single-host environment
allows (reference analog: mpu/random.py RNG tracker discipline + the dist
loss parity the reference asserts across its collective tests).

Canary: `rng_drift` trains a dropout-bearing model twice under mp2 — once
clean, once with the training-time RNG stream perturbed (the per-axis drift
failure mode) — and must show divergence well beyond the parity tolerance,
proving the gate has teeth.

Run standalone:  python tools/parallel_parity.py [steps] > curves.json
(the committed 200-step curves live in docs/parallel_parity_curves.json)
"""
from __future__ import annotations

import os
import sys

import numpy as np

CFG = dict(vocab=512, hidden=128, inter=256, layers=4, heads=4, seq=64,
           batch=8, lr=3e-4, wd=0.01, betas=(0.9, 0.999), eps=1e-8, pool=8)

MODES = ("single", "dp2", "mp2", "zero2", "pp2_1f1b", "pp2_zbh1")


def _data_pool(cfg=CFG, seed=1234):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg["vocab"], (cfg["batch"], cfg["seq"]))
            .astype(np.int64) for _ in range(cfg["pool"])]


def _modules(cfg=CFG):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (
        LlamaDecoderLayer, LlamaPretrainingCriterion, _EmbeddingStage,
        _HeadStage, llama_tiny_config)

    lcfg = llama_tiny_config(
        vocab_size=cfg["vocab"], hidden_size=cfg["hidden"],
        intermediate_size=cfg["inter"], num_hidden_layers=cfg["layers"],
        num_attention_heads=cfg["heads"], num_key_value_heads=cfg["heads"],
        max_position_embeddings=cfg["seq"], use_parallel_cross_entropy=False)
    paddle.seed(0)
    embed = _EmbeddingStage(lcfg)
    blocks = [LlamaDecoderLayer(lcfg) for _ in range(lcfg.num_hidden_layers)]
    head = _HeadStage(lcfg)
    crit = LlamaPretrainingCriterion(lcfg)
    return embed, blocks, head, crit


def run_mode(mode: str, steps: int, cfg=CFG):
    """Train `steps` on the given mode; returns the loss curve (floats)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh, set_mesh
    from paddle_tpu.parallel.pipeline import PipelinedTrainStep
    from paddle_tpu.parallel.train_step import CompiledTrainStep
    from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep

    set_mesh(None)
    embed, blocks, head, crit = _modules(cfg)
    params = (embed.parameters()
              + [p for b in blocks for p in b.parameters()]
              + head.parameters())
    opt = paddle.optimizer.AdamW(
        learning_rate=cfg["lr"], beta1=cfg["betas"][0], beta2=cfg["betas"][1],
        epsilon=cfg["eps"], weight_decay=cfg["wd"], parameters=params)

    def loss_fn(logits, labels):
        return crit(logits, labels)

    if mode == "single":
        mesh = build_mesh({"dp": 1})
        step = _seq_step(embed, blocks, head, crit, opt, mesh)
    elif mode == "dp2":
        mesh = build_mesh({"dp": 2})
        step = _seq_step(embed, blocks, head, crit, opt, mesh)
    elif mode == "mp2":
        mesh = build_mesh({"dp": 1, "mp": 2})
        step = _seq_step(embed, blocks, head, crit, opt, mesh)
    elif mode == "zero2":
        mesh = build_mesh({"dp": 2})
        step = _seq_step(embed, blocks, head, crit, opt, mesh,
                         zero_axis="dp")
    elif mode == "pp2_1f1b":
        mesh = build_mesh({"pp": 2})
        step = PipelinedTrainStep(embed, blocks, head, loss_fn,
                                  optimizer=opt, mesh=mesh, num_micro=2,
                                  remat=False)
    elif mode == "pp2_zbh1":
        mesh = build_mesh({"pp": 2})
        step = ZBH1PipelinedStep(embed, blocks, head, loss_fn, mesh=mesh,
                                 num_micro=2, optimizer=opt)
    else:
        raise ValueError(mode)

    pool = _data_pool(cfg)
    losses = []
    for i in range(steps):
        ids = paddle.to_tensor(pool[i % len(pool)])
        losses.append(float(step(ids, ids)))
    set_mesh(None)
    return losses


def _seq_step(embed, blocks, head, crit, opt, mesh, zero_axis=None):
    from paddle_tpu.parallel.train_step import CompiledTrainStep

    params = (embed.parameters()
              + [p for b in blocks for p in b.parameters()]
              + head.parameters())

    class _Seq:
        def parameters(self):
            return params

        def __call__(self, ids, labels):
            x = embed(ids)
            for b in blocks:
                x = b(x)
            return crit(head(x), labels)

    inner = CompiledTrainStep(_Seq(), lambda out, lab: out, optimizer=opt,
                              mesh=mesh, zero_axis=zero_axis)
    return lambda ids, labels: inner(ids, labels, labels)


# ---------------------------------------------------------------------------
# RNG-drift canary: dropout model under mp2, clean vs perturbed stream


def run_rng_canary(steps: int, perturb: bool, cfg=CFG):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.mesh import build_mesh, set_mesh
    from paddle_tpu.parallel.train_step import CompiledTrainStep

    set_mesh(None)
    mesh = build_mesh({"dp": 1, "mp": 2})
    paddle.seed(0)

    class DropMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(cfg["vocab"], cfg["hidden"])
            self.fc1 = nn.Linear(cfg["hidden"], cfg["inter"])
            self.drop = nn.Dropout(0.2)
            self.fc2 = nn.Linear(cfg["inter"], cfg["vocab"])

        def forward(self, ids, labels):
            import paddle_tpu.nn.functional as F

            x = self.drop(paddle.tanh(self.fc1(self.emb(ids))))
            logits = self.fc2(x)
            return F.cross_entropy(
                logits.reshape([-1, cfg["vocab"]]), labels.reshape([-1]))

    model = DropMLP()
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=cfg["lr"],
                                 parameters=model.parameters())
    # the per-axis RNG drift failure mode: the step's dropout key stream
    # desyncs from the reference run's
    step = CompiledTrainStep(model, lambda out, lab: out, optimizer=opt,
                             mesh=mesh, seed=1337 if perturb else 0)
    pool = _data_pool(cfg)
    losses = []
    for i in range(steps):
        ids = paddle.to_tensor(pool[i % len(pool)])
        losses.append(float(step(ids, ids, ids)))
    set_mesh(None)
    return losses


def run_all(steps: int = 200):
    curves = {m: run_mode(m, steps) for m in MODES}
    base = np.asarray(curves["single"])
    devs = {m: float(np.max(np.abs(np.asarray(curves[m]) - base)))
            for m in MODES if m != "single"}
    clean = run_rng_canary(steps, perturb=False)
    drifted = run_rng_canary(steps, perturb=True)
    canary_dev = float(np.max(np.abs(np.asarray(clean) - np.asarray(drifted))))
    return curves, devs, canary_dev


if __name__ == "__main__":
    import json

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    curves, devs, canary_dev = run_all(steps)
    for m, d in devs.items():
        print(f"{m}: max |dev| vs single over {steps} steps = {d:.6f}",
              file=sys.stderr)
    print(f"rng-drift canary dev = {canary_dev:.4f}", file=sys.stderr)
    print(json.dumps({"steps": steps, "curves": curves, "max_devs": devs,
                      "rng_canary_dev": canary_dev}))
